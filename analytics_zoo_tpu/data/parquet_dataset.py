"""Parquet dataset writer/readers for image-style records.

Reference: `pyzoo/zoo/orca/data/image/parquet_dataset.py:30-186`
(ParquetDataset.write from a record generator + schema, read back as
XShards / tf.data / torch; `write_mnist`, `write_ndarrays` helpers).
Here pyarrow writes the blocks and the readers hand back XShards or a
TPUDataset.
"""

from __future__ import annotations

import os
import shutil
from typing import Any, Dict, Iterable, Optional, Sequence

import numpy as np

from analytics_zoo_tpu.data.shards import XShards


class _NdarraySchema:
    """Marks a field as an ndarray (stored as bytes + shape columns)."""

    def __init__(self, shape: Optional[Sequence[int]] = None,
                 dtype=np.float32):
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = np.dtype(dtype)


SchemaField = _NdarraySchema  # public alias


class ParquetDataset:
    @staticmethod
    def write(path: str, generator: Iterable[Dict],
              schema: Dict[str, Any], block_size: int = 1000,
              write_mode: str = "overwrite"):
        """Write records from `generator` (dicts of field → value) into
        parquet blocks under `path`. ndarray-typed fields (schema value is
        a SchemaField) serialize as raw bytes + shape."""
        import pyarrow as pa
        import pyarrow.parquet as pq
        if os.path.exists(path):
            if write_mode == "overwrite":
                shutil.rmtree(path)
            elif write_mode == "error":
                raise FileExistsError(path)
        os.makedirs(path, exist_ok=True)

        def flush(rows, idx):
            if not rows:
                return
            cols: Dict[str, list] = {}
            for r in rows:
                for k, v in r.items():
                    cols.setdefault(k, []).append(v)
            arrays, names = [], []
            for k, vals in cols.items():
                field_schema = schema.get(k)
                if isinstance(field_schema, _NdarraySchema):
                    # NOT ascontiguousarray: it promotes 0-d to (1,)
                    arrs = [np.asarray(v, field_schema.dtype)
                            for v in vals]
                    arrays.append(pa.array([a.tobytes() for a in arrs]))
                    names.append(k)
                    arrays.append(pa.array([list(a.shape) for a in arrs],
                                           pa.list_(pa.int32())))
                    names.append(k + "__shape")
                    arrays.append(pa.array(
                        [str(field_schema.dtype)] * len(arrs)))
                    names.append(k + "__dtype")
                else:
                    arrays.append(pa.array(vals))
                    names.append(k)
            table = pa.table(arrays, names=names)
            pq.write_table(table,
                           os.path.join(path, f"part-{idx:05d}.parquet"))

        rows, idx = [], 0
        for rec in generator:
            rows.append(rec)
            if len(rows) >= block_size:
                flush(rows, idx)
                rows, idx = [], idx + 1
        flush(rows, idx)
        return path

    @staticmethod
    def _decode_table(table) -> Dict[str, np.ndarray]:
        cols = table.column_names
        out: Dict[str, np.ndarray] = {}
        for name in cols:
            if name.endswith("__shape") or name.endswith("__dtype"):
                continue
            if name + "__shape" in cols:
                blobs = table.column(name).to_pylist()
                shapes = table.column(name + "__shape").to_pylist()
                dtypes = table.column(name + "__dtype").to_pylist()
                out[name] = np.stack([
                    np.frombuffer(b, dtype=np.dtype(d)).reshape(s)
                    for b, s, d in zip(blobs, shapes, dtypes)])
            else:
                out[name] = np.asarray(table.column(name).to_pylist())
        return out

    @staticmethod
    def read_as_xshards(path: str,
                        pipeline_workers: Optional[int] = None) -> XShards:
        """One shard per parquet block (`_read_as_xshards`). Blocks
        read+decode concurrently on the input-pipeline worker pool
        (shard order preserved; a bad part file raises one error
        naming it)."""
        import pyarrow.parquet as pq
        from analytics_zoo_tpu.data.pipeline import parallel_read
        parts = sorted(
            os.path.join(path, f) for f in os.listdir(path)
            if f.endswith(".parquet"))
        shards = parallel_read(
            parts, lambda p: ParquetDataset._decode_table(pq.read_table(p)),
            workers=pipeline_workers)
        return XShards(shards)

    @staticmethod
    def read_as_dataset(path: str, feature_col: str = "image",
                        label_col: Optional[str] = "label",
                        batch_size: int = -1, batch_per_thread: int = -1):
        """Straight to a TPUDataset (`read_as_tf` analogue)."""
        from analytics_zoo_tpu.data.dataset import TPUDataset
        merged: Dict[str, list] = {}
        for shard in ParquetDataset.read_as_xshards(path).collect():
            for k, v in shard.items():
                merged.setdefault(k, []).append(v)
        data = {k: np.concatenate(v) for k, v in merged.items()}
        x = data[feature_col]
        y = data.get(label_col) if label_col else None
        return TPUDataset.from_ndarrays((x, y) if y is not None else x,
                                        batch_size, batch_per_thread)


def write_ndarrays(images: np.ndarray, labels: np.ndarray, output_path: str,
                   **kwargs) -> str:
    """`_write_ndarrays` (parquet_dataset.py:166)."""
    schema = {"image": _NdarraySchema(images.shape[1:], images.dtype),
              "label": _NdarraySchema(labels.shape[1:], labels.dtype)}

    def gen():
        for i in range(len(images)):
            yield {"image": images[i], "label": labels[i]}

    return ParquetDataset.write(output_path, gen(), schema, **kwargs)


def write_mnist(image_file: str, label_file: str, output_path: str,
                **kwargs) -> str:
    """IDX-format MNIST → parquet (`write_mnist`, parquet_dataset.py:186)."""
    import gzip

    def _open(p):
        return gzip.open(p, "rb") if p.endswith(".gz") else open(p, "rb")

    def _read32(f):
        return int.from_bytes(f.read(4), "big")

    with _open(image_file) as f:
        magic = _read32(f)
        if magic != 2051:
            raise ValueError(f"Bad MNIST image magic {magic}")
        n, rows, cols = _read32(f), _read32(f), _read32(f)
        images = np.frombuffer(f.read(n * rows * cols), np.uint8).reshape(
            n, rows, cols, 1)
    with _open(label_file) as f:
        magic = _read32(f)
        if magic != 2049:
            raise ValueError(f"Bad MNIST label magic {magic}")
        n2 = _read32(f)
        labels = np.frombuffer(f.read(n2), np.uint8)
    return write_ndarrays(images, labels, output_path, **kwargs)
