"""Detection dataset readers (Pascal VOC / COCO) + SSD batching.

Reference: `models/image/objectdetection/common/dataset/PascalVoc.scala`
(VOCdevkit layout — `ImageSets/Main/<set>.txt`, `Annotations/<id>.xml`,
`JPEGImages/<id>.jpg` — and the 20-class table), `Coco.scala` (per-image
JSON annotations listed by an `ImageSets/<set>.txt` of
"<image> <annotation>" pairs, COCO category-id remap), `Imdb.scala`
(`getImdb("voc_2007_train", path)` factory), and `ssd/SSDMiniBatch.scala`
(batched images + gt rows `(imgId, label, diff, x1, y1, x2, y2)`).

TPU-first deltas from the reference: class indices are **0-based with 0 =
background** (the convention `models/objectdetection.py` trains with;
the reference stores 1-based-with-background-at-1) and batches are
fixed-shape — per-image gts pad to `max_gt` so the whole train step jits
(the reference's variable-length gt tensor would retrace per batch).
"""

from __future__ import annotations

import glob
import json
import os
import xml.etree.ElementTree as ET
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from analytics_zoo_tpu.data.roi import (RoiChain, RoiLabel,
                                        ssd_train_transforms,
                                        ssd_val_transforms)

# `PascalVoc.scala` classes table (background first)
VOC_CLASSES: Tuple[str, ...] = (
    "__background__",
    "aeroplane", "bicycle", "bird", "boat",
    "bottle", "bus", "car", "cat", "chair",
    "cow", "diningtable", "dog", "horse",
    "motorbike", "person", "pottedplant",
    "sheep", "sofa", "train", "tvmonitor",
)
VOC_CLASS_TO_IND: Dict[str, int] = {c: i for i, c in enumerate(VOC_CLASSES)}

# `Coco.scala` category-id ↔ name table (ids are sparse: 80 classes over
# id range 1..90; background id 0 first)
COCO_CAT_ID_AND_CLASS: Tuple[Tuple[int, str], ...] = (
    (0, "__background__"),
    (1, "person"), (2, "bicycle"), (3, "car"), (4, "motorcycle"),
    (5, "airplane"), (6, "bus"), (7, "train"), (8, "truck"), (9, "boat"),
    (10, "traffic light"), (11, "fire hydrant"), (13, "stop sign"),
    (14, "parking meter"), (15, "bench"), (16, "bird"), (17, "cat"),
    (18, "dog"), (19, "horse"), (20, "sheep"), (21, "cow"),
    (22, "elephant"), (23, "bear"), (24, "zebra"), (25, "giraffe"),
    (27, "backpack"), (28, "umbrella"), (31, "handbag"), (32, "tie"),
    (33, "suitcase"), (34, "frisbee"), (35, "skis"), (36, "snowboard"),
    (37, "sports ball"), (38, "kite"), (39, "baseball bat"),
    (40, "baseball glove"), (41, "skateboard"), (42, "surfboard"),
    (43, "tennis racket"), (44, "bottle"), (46, "wine glass"), (47, "cup"),
    (48, "fork"), (49, "knife"), (50, "spoon"), (51, "bowl"),
    (52, "banana"), (53, "apple"), (54, "sandwich"), (55, "orange"),
    (56, "broccoli"), (57, "carrot"), (58, "hot dog"), (59, "pizza"),
    (60, "donut"), (61, "cake"), (62, "chair"), (63, "couch"),
    (64, "potted plant"), (65, "bed"), (67, "dining table"), (70, "toilet"),
    (72, "tv"), (73, "laptop"), (74, "mouse"), (75, "remote"),
    (76, "keyboard"), (77, "cell phone"), (78, "microwave"), (79, "oven"),
    (80, "toaster"), (81, "sink"), (82, "refrigerator"), (84, "book"),
    (85, "clock"), (86, "vase"), (87, "scissors"), (88, "teddy bear"),
    (89, "hair drier"), (90, "toothbrush"),
)
COCO_CLASSES: Tuple[str, ...] = tuple(c for _, c in COCO_CAT_ID_AND_CLASS)
COCO_CAT_ID_TO_IND: Dict[int, int] = {
    cid: i for i, (cid, _) in enumerate(COCO_CAT_ID_AND_CLASS)}


class DetectionFeature:
    """One roidb entry: decoded RGB image (or None), RoiLabel, source path
    (the reference's `ImageFeature(image, label, path)`)."""

    __slots__ = ("image", "roi", "path")

    def __init__(self, image: Optional[np.ndarray], roi: RoiLabel,
                 path: str):
        self.image = image
        self.roi = roi
        self.path = path


def load_voc_annotation(xml_path: str,
                        class_to_ind: Dict[str, int] = VOC_CLASS_TO_IND
                        ) -> RoiLabel:
    """Parse one `Annotations/<id>.xml` (`PascalVoc.loadAnnotation`):
    bndbox corners in pixel coords, class name, difficult flag."""
    root = ET.parse(xml_path).getroot()
    objs = root.findall("object")
    boxes = np.zeros((len(objs), 4), np.float32)
    classes = np.zeros((len(objs),), np.int32)
    difficult = np.zeros((len(objs),), np.float32)
    for i, obj in enumerate(objs):
        bb = obj.find("bndbox")
        boxes[i] = [float(bb.find(t).text)
                    for t in ("xmin", "ymin", "xmax", "ymax")]
        classes[i] = class_to_ind[obj.find("name").text.strip()]
        diff = obj.find("difficult")
        difficult[i] = float(diff.text) if diff is not None else 0.0
    return RoiLabel(classes, boxes, difficult)


def load_coco_annotation(json_path: str) -> RoiLabel:
    """Parse one per-image COCO-style JSON (`Coco.loadAnnotation`):
    `{"image": {width, height}, "annotation": [{area, bbox[x,y,w,h],
    category_id}, ...]}` — xywh → clipped corners, zero-area dropped,
    difficult always 0."""
    with open(json_path) as fh:
        blob = json.load(fh)
    width = float(blob["image"]["width"])
    height = float(blob["image"]["height"])
    boxes, classes = [], []
    for ann in blob.get("annotation", []):
        x, y, w, h = [float(v) for v in ann["bbox"]]
        x1, y1 = max(0.0, x), max(0.0, y)
        x2 = min(width - 1.0, x1 + max(0.0, w - 1.0))
        y2 = min(height - 1.0, y1 + max(0.0, h - 1.0))
        if float(ann.get("area", w * h)) > 0 and x2 >= x1 and y2 >= y1:
            boxes.append([x1, y1, x2, y2])
            classes.append(COCO_CAT_ID_TO_IND[int(ann["category_id"])])
    return RoiLabel(np.asarray(classes, np.int32),
                    np.asarray(boxes, np.float32).reshape(-1, 4))


class Imdb:
    """Image database: `get_roidb()` -> list of DetectionFeature
    (`Imdb.scala` trait + `getImdb` name factory)."""

    classes: Tuple[str, ...] = ()

    def get_roidb(self, read_image: bool = True) -> List[DetectionFeature]:
        raise NotImplementedError

    @staticmethod
    def get_imdb(name: str, devkit_path: str) -> "Imdb":
        parts = name.split("_")
        if parts[0] == "voc":
            return PascalVoc(image_set=parts[2], devkit_path=devkit_path,
                             year=parts[1])
        if parts[0] == "coco":
            return Coco(image_set=parts[1], devkit_path=devkit_path)
        raise ValueError(f"Unknown imdb name {name!r} "
                         "(expected voc_<year>_<set> or coco_<set>)")

    @staticmethod
    def _read_image(path: str) -> np.ndarray:
        from analytics_zoo_tpu.data.image import load_image
        return load_image(path)


class PascalVoc(Imdb):
    """VOCdevkit reader (`PascalVoc.scala`): year "0712" merges 2007+2012
    the way the reference trains SSD."""

    classes = VOC_CLASSES

    def __init__(self, image_set: str, devkit_path: str,
                 year: str = "2007"):
        if not os.path.isdir(devkit_path):
            raise FileNotFoundError(
                f"VOCdevkit path does not exist: {devkit_path}")
        self.image_set = image_set
        self.devkit_path = devkit_path
        self.year = year
        self.name = f"voc_{year}_{image_set}"

    def _index_paths(self) -> List[Tuple[str, str]]:
        years = ("2007", "2012") if self.year == "0712" else (self.year,)
        pairs = []
        for y in years:
            data = os.path.join(self.devkit_path, f"VOC{y}")
            if not os.path.isdir(data):
                raise FileNotFoundError(
                    f"cannot find data folder {data} for {self.name}")
            lst = os.path.join(data, "ImageSets", "Main",
                               f"{self.image_set}.txt")
            if not os.path.exists(lst):
                raise FileNotFoundError(f"Path does not exist {lst}")
            with open(lst) as fh:
                for line in fh:
                    idx = line.strip()
                    if idx:
                        pairs.append(
                            (os.path.join(data, "JPEGImages",
                                          f"{idx}.jpg"),
                             os.path.join(data, "Annotations",
                                          f"{idx}.xml")))
        return pairs

    def get_roidb(self, read_image: bool = True) -> List[DetectionFeature]:
        out = []
        for img_path, ann_path in self._index_paths():
            img = self._read_image(img_path) if read_image else None
            out.append(DetectionFeature(
                img, load_voc_annotation(ann_path), img_path))
        return out


class Coco(Imdb):
    """Reference COCO layout (`Coco.scala`): `ImageSets/<set>.txt` lines
    of "<image-relpath> <annotation-relpath>", per-image JSON files."""

    classes = COCO_CLASSES

    def __init__(self, image_set: str, devkit_path: str):
        self.image_set = image_set
        self.devkit_path = devkit_path
        self.name = f"coco_{image_set}"

    def get_roidb(self, read_image: bool = True) -> List[DetectionFeature]:
        lst = os.path.join(self.devkit_path, "ImageSets",
                           f"{self.image_set}.txt")
        if not os.path.exists(lst):
            raise FileNotFoundError(f"Path does not exist {lst}")
        out = []
        with open(lst) as fh:
            for line in fh:
                parts = line.split()
                if not parts:
                    continue
                img_path = os.path.join(self.devkit_path, parts[0])
                ann_path = os.path.join(self.devkit_path, parts[1])
                img = self._read_image(img_path) if read_image else None
                out.append(DetectionFeature(
                    img, load_coco_annotation(ann_path), img_path))
        return out


# ---------------------------------------------------------------------------
# SSD batching (`ssd/SSDMiniBatch.scala` / `RoiImageToSSDBatch.scala`)
# ---------------------------------------------------------------------------
def features_to_ssd_arrays(features: Sequence[DetectionFeature],
                           transforms: Optional[RoiChain],
                           max_gt: int,
                           normalize=None
                           ) -> Tuple[np.ndarray, Dict[str, np.ndarray]]:
    """Run the roi chain per feature and assemble the fixed-shape arrays
    the jitted SSD step consumes: images [N,H,W,3] float32 and padded
    `{"gt_boxes": [N,G,4] normalized corners, "gt_labels": [N,G] int32
    (0 pad), "difficult": [N,G]}`. `normalize` is an optional image-only
    op applied last (channel normalize / dtype), shared with eval."""
    imgs, boxes, labels, diffs = [], [], [], []
    for feat in features:
        img, roi = feat.image, feat.roi
        if transforms is not None:
            img, roi = transforms.apply(img, roi)
        if normalize is not None:
            img = normalize(img)
        g = min(len(roi), max_gt)
        if len(roi) > max_gt:
            import logging
            logging.getLogger(__name__).warning(
                "%s: %d ground truths truncated to max_gt=%d — evaluation "
                "on these arrays will under-count npos; raise max_gt",
                feat.path, len(roi), max_gt)
        b = np.zeros((max_gt, 4), np.float32)
        c = np.zeros((max_gt,), np.int32)
        d = np.zeros((max_gt,), np.float32)
        b[:g] = roi.boxes[:g]
        c[:g] = roi.classes[:g]
        d[:g] = roi.difficult[:g]
        imgs.append(np.asarray(img, np.float32))
        boxes.append(b)
        labels.append(c)
        diffs.append(d)
    return (np.stack(imgs),
            {"gt_boxes": np.stack(boxes), "gt_labels": np.stack(labels),
             "difficult": np.stack(diffs)})


def gt_arrays_to_rows(gt: Dict[str, np.ndarray]) -> np.ndarray:
    """Padded gt arrays -> the evaluator's flat row form
    `[M, 7] = (img_id, label, difficult, x1, y1, x2, y2)`
    (`SSDMiniBatch` target layout; pad rows dropped)."""
    rows = []
    n = gt["gt_labels"].shape[0]
    for i in range(n):
        keep = gt["gt_labels"][i] > 0
        for lab, diff, box in zip(gt["gt_labels"][i][keep],
                                  gt["difficult"][i][keep],
                                  gt["gt_boxes"][i][keep]):
            rows.append([i, lab, diff, *box])
    return np.asarray(rows, np.float32).reshape(-1, 7)


def load_ssd_train_set(imdb_or_name, devkit_path: Optional[str] = None,
                       resolution: int = 300, max_gt: int = 32,
                       means: Sequence[float] = (123.0, 117.0, 104.0),
                       seed: Optional[int] = 0, normalize=None):
    """`SSDDataSet.loadSSDTrainSet`: read roidb, apply the augmenting
    chain, return (images, gt-dict) ready for `TPUDataset`/`fit`."""
    imdb = (Imdb.get_imdb(imdb_or_name, devkit_path)
            if isinstance(imdb_or_name, str) else imdb_or_name)
    chain = ssd_train_transforms(resolution, means=means, seed=seed)
    return features_to_ssd_arrays(imdb.get_roidb(), chain, max_gt,
                                  normalize=normalize)


def load_ssd_val_set(imdb_or_name, devkit_path: Optional[str] = None,
                     resolution: int = 300, max_gt: int = 32,
                     normalize=None):
    """`SSDDataSet.loadSSDValSet`: no augmentation, same batch contract."""
    imdb = (Imdb.get_imdb(imdb_or_name, devkit_path)
            if isinstance(imdb_or_name, str) else imdb_or_name)
    chain = ssd_val_transforms(resolution)
    return features_to_ssd_arrays(imdb.get_roidb(), chain, max_gt,
                                  normalize=normalize)
