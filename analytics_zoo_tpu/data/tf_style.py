"""tf.data-style Dataset API over XShards.

Reference: `pyzoo/zoo/orca/data/tf/data.py:124-221` — `Dataset` wraps
XShards with lazily-composed per-shard transforms (`from_tensor_slices`,
`map`), consumed by the estimators. Here the composed pipeline resolves to
a TPUDataset at fit/predict time.
"""

from __future__ import annotations

from typing import Callable

import numpy as np

from analytics_zoo_tpu.data.shards import XShards


class Dataset:
    """Lazy per-element transform pipeline over sharded data."""

    def __init__(self, xshards: XShards, transforms=None):
        self.xshards = xshards
        self.transforms = list(transforms or [])

    @staticmethod
    def from_tensor_slices(xshards: XShards) -> "Dataset":
        """`Dataset.from_tensor_slices` (data.py:190): elements are rows of
        the shards' arrays/dicts/tuples."""
        if not isinstance(xshards, XShards):
            xshards = XShards.partition(xshards)
        return Dataset(xshards)

    def map(self, map_func: Callable) -> "Dataset":
        """`map` (data.py:193): per-element transform, applied lazily."""
        return Dataset(self.xshards, self.transforms + [map_func])

    # -- materialization ---------------------------------------------------
    def _apply(self, shard):
        import jax
        n = len(jax.tree_util.tree_leaves(shard)[0])
        rows = []
        for i in range(n):
            row = jax.tree_util.tree_map(lambda a: a[i], shard)
            for fn in self.transforms:
                row = fn(row)
            rows.append(row)
        return jax.tree_util.tree_map(lambda *xs: np.stack(xs), *rows)

    def to_xshards(self) -> XShards:
        return self.xshards.transform_shard(self._apply)

    def to_dataset(self, batch_size: int = -1, batch_per_thread: int = -1):
        from analytics_zoo_tpu.data.dataset import TPUDataset
        return TPUDataset.from_xshards(self.to_xshards(), batch_size,
                                       batch_per_thread)
