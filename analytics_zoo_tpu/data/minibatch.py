"""MiniBatch construction with the reference's padding semantics.

The reference batches Samples into `MiniBatch`es with optional
`PaddingParam`s (BigDL SampleToMiniBatch, wrapped at
`zoo/.../tfpark/SampleToMiniBatch.scala`, `TFMiniBatch.scala`): features and
labels are (possibly nested) tensor lists; variable-length tensors are padded
to the batch max or to a fixed `paddingLen` with a pad value. On TPU, fixed
padding is the important case — static shapes keep one compiled program
(`hard_code_batch_size` analogue, `tf_dataset.py:158-173`).
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Union

import numpy as np


class PaddingParam:
    """Padding spec (BigDL PaddingParam): pad value + optional fixed length
    per dimension (-1 → batch max)."""

    def __init__(self, value: float = 0.0,
                 fixed_length: Optional[Sequence[int]] = None):
        self.value = value
        self.fixed_length = list(fixed_length) if fixed_length else None


def _pad_to(arr: np.ndarray, target_shape: Sequence[int],
            value: float) -> np.ndarray:
    pads = [(0, t - s) for s, t in zip(arr.shape, target_shape)]
    if any(p[1] < 0 for p in pads):
        raise ValueError(
            f"Sample shape {arr.shape} exceeds fixed padding {target_shape}")
    if all(p[1] == 0 for p in pads):
        return arr
    return np.pad(arr, pads, constant_values=value)


def batch_samples(samples: Sequence[Any],
                  padding: Optional[PaddingParam] = None) -> Any:
    """Stack a list of per-sample pytrees into one batched pytree, padding
    ragged tensors (the SampleToMiniBatch contract)."""
    import jax
    first = samples[0]
    treedef = jax.tree_util.tree_structure(first)
    leaves_per_sample = [jax.tree_util.tree_flatten(s)[0] for s in samples]
    batched = []
    for i in range(len(leaves_per_sample[0])):
        arrs = [np.asarray(ls[i]) for ls in leaves_per_sample]
        shapes = np.array([a.shape for a in arrs])
        if padding is not None and padding.fixed_length is not None:
            target = list(padding.fixed_length)
            for d in range(len(target)):
                if target[d] == -1:
                    target[d] = int(shapes[:, d].max())
        else:
            target = list(shapes.max(axis=0))
        value = padding.value if padding else 0.0
        if not (shapes == shapes[0]).all() or padding is not None:
            arrs = [_pad_to(a, target, value) for a in arrs]
        batched.append(np.stack(arrs))
    return jax.tree_util.tree_unflatten(treedef, batched)


def pad_sequences(seqs: Sequence[Sequence[int]], maxlen: int,
                  value: int = 0, truncating: str = "post",
                  padding: str = "post", dtype=np.int32) -> np.ndarray:
    """Keras-style sequence padding used by the text pipeline
    (`TextSet.shapeSequence`, `feature/text/TextSet.scala`)."""
    out = np.full((len(seqs), maxlen), value, dtype=dtype)
    for i, s in enumerate(seqs):
        s = list(s)
        if len(s) > maxlen:
            s = s[-maxlen:] if truncating == "pre" else s[:maxlen]
        if padding == "pre":
            out[i, maxlen - len(s):] = s
        else:
            out[i, :len(s)] = s
    return out
