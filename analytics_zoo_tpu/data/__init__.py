from analytics_zoo_tpu.data.shards import XShards, SparkXShards  # noqa: F401
from analytics_zoo_tpu.data.dataset import TPUDataset  # noqa: F401
from analytics_zoo_tpu.data.feature_set import FeatureSet  # noqa: F401
from analytics_zoo_tpu.data import readers  # noqa: F401
from analytics_zoo_tpu.data import tfrecord  # noqa: F401
from analytics_zoo_tpu.data.readers import (  # noqa: F401
    read_csv, read_json, read_parquet)
from analytics_zoo_tpu.data.roi import RoiLabel  # noqa: F401
from analytics_zoo_tpu.data.detection import (  # noqa: F401
    Coco, Imdb, PascalVoc)
