"""3-D image (volume) preprocessing.

Reference: `zoo/.../feature/image3d/` (Affine.scala, Cropper.scala,
Rotation.scala) and the python mirror
`pyzoo/zoo/feature/image3d/transformation.py:37-102` (Crop3D, RandomCrop3D,
CenterCrop3D, Rotate3D, AffineTransform3D). Volumes are [D, H, W] or
[D, H, W, C] float arrays; transforms run host-side per record (the same
place the reference runs them — inside the data pipeline, not the model).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from analytics_zoo_tpu.data.image import ImageProcessing


def _split_channels(vol: np.ndarray):
    if vol.ndim == 3:
        return vol[..., None], True
    return vol, False


class ImageProcessing3D(ImageProcessing):
    """Marker base (`ImagePreprocessing3D`, transformation.py:29)."""


class Crop3D(ImageProcessing3D):
    """`Crop3D(start, patch_size)` (transformation.py:37): crop
    patch_size = [d, h, w] starting at start = [d0, h0, w0]."""

    def __init__(self, start: Sequence[int], patch_size: Sequence[int]):
        self.start = tuple(int(v) for v in start)
        self.patch_size = tuple(int(v) for v in patch_size)

    def apply(self, vol: np.ndarray) -> np.ndarray:
        d0, h0, w0 = self.start
        d, h, w = self.patch_size
        if d0 + d > vol.shape[0] or h0 + h > vol.shape[1] \
                or w0 + w > vol.shape[2]:
            raise ValueError(
                f"Crop {self.start}+{self.patch_size} exceeds volume "
                f"shape {vol.shape[:3]}")
        return vol[d0:d0 + d, h0:h0 + h, w0:w0 + w]


class CenterCrop3D(ImageProcessing3D):
    def __init__(self, crop_depth: int, crop_height: int, crop_width: int):
        self.size = (crop_depth, crop_height, crop_width)

    def apply(self, vol: np.ndarray) -> np.ndarray:
        starts = [(s - c) // 2 for s, c in zip(vol.shape[:3], self.size)]
        return Crop3D(starts, self.size).apply(vol)


class RandomCrop3D(ImageProcessing3D):
    def __init__(self, crop_depth: int, crop_height: int, crop_width: int,
                 seed: Optional[int] = None):
        self.size = (crop_depth, crop_height, crop_width)
        self.rng = np.random.RandomState(seed)

    def apply(self, vol: np.ndarray) -> np.ndarray:
        starts = [self.rng.randint(0, s - c + 1)
                  for s, c in zip(vol.shape[:3], self.size)]
        return Crop3D(starts, self.size).apply(vol)


class AffineTransform3D(ImageProcessing3D):
    """`AffineTransform3D(affine_mat, translation, clamp_mode)`
    (transformation.py:88 / Affine.scala): resample the volume through an
    affine map around the volume center with trilinear interpolation.
    clamp_mode 'clamp' edge-extends; 'padding' fills with pad_value."""

    def __init__(self, affine_mat: np.ndarray,
                 translation: Optional[np.ndarray] = None,
                 clamp_mode: str = "clamp", pad_value: float = 0.0):
        self.mat = np.asarray(affine_mat, np.float64).reshape(3, 3)
        self.translation = (np.zeros(3) if translation is None
                            else np.asarray(translation, np.float64))
        if clamp_mode not in ("clamp", "padding"):
            raise ValueError(f"Unsupported clamp_mode: {clamp_mode}")
        self.clamp_mode = clamp_mode
        self.pad_value = float(pad_value)

    def apply(self, vol: np.ndarray) -> np.ndarray:
        v, squeeze = _split_channels(np.asarray(vol, np.float32))
        D, H, W, C = v.shape
        center = (np.asarray([D, H, W], np.float64) - 1.0) / 2.0
        # output grid coords → source coords: src = A·(dst−c) + c + t
        dz, dy, dx = np.meshgrid(np.arange(D), np.arange(H), np.arange(W),
                                 indexing="ij")
        dst = np.stack([dz, dy, dx], axis=-1).reshape(-1, 3).astype(
            np.float64)
        src = (dst - center) @ self.mat.T + center + self.translation

        if self.clamp_mode == "clamp":
            src = np.clip(src, 0, np.asarray([D - 1, H - 1, W - 1],
                                             np.float64))
            valid = np.ones(len(src), bool)
        else:
            valid = np.all((src >= 0)
                           & (src <= [D - 1, H - 1, W - 1]), axis=1)
            src = np.clip(src, 0, np.asarray([D - 1, H - 1, W - 1],
                                             np.float64))

        lo = np.floor(src).astype(np.int64)
        hi = np.minimum(lo + 1, [D - 1, H - 1, W - 1])
        f = (src - lo).astype(np.float32)

        def gather(zi, yi, xi):
            return v[zi, yi, xi]                       # [N, C]

        out = np.zeros((len(src), C), np.float32)
        for bz, wz in ((lo[:, 0], 1 - f[:, 0]), (hi[:, 0], f[:, 0])):
            for by, wy in ((lo[:, 1], 1 - f[:, 1]), (hi[:, 1], f[:, 1])):
                for bx, wx in ((lo[:, 2], 1 - f[:, 2]), (hi[:, 2], f[:, 2])):
                    out += gather(bz, by, bx) * (wz * wy * wx)[:, None]
        if self.clamp_mode == "padding":
            out[~valid] = self.pad_value
        out = out.reshape(D, H, W, C)
        return out[..., 0] if squeeze else out


class Rotate3D(AffineTransform3D):
    """`Rotate3D(rotation_angles)` (transformation.py:75 / Rotation.scala):
    intrinsic rotations (radians) around the z, y, x axes applied around
    the volume center."""

    def __init__(self, rotation_angles: Sequence[float],
                 clamp_mode: str = "clamp", pad_value: float = 0.0):
        az, ay, ax = (float(a) for a in rotation_angles)
        cz, sz = np.cos(az), np.sin(az)
        cy, sy = np.cos(ay), np.sin(ay)
        cx, sx = np.cos(ax), np.sin(ax)
        rz = np.asarray([[1, 0, 0], [0, cz, -sz], [0, sz, cz]])
        ry = np.asarray([[cy, 0, sy], [0, 1, 0], [-sy, 0, cy]])
        rx = np.asarray([[cx, -sx, 0], [sx, cx, 0], [0, 0, 1]])
        super().__init__(rz @ ry @ rx, clamp_mode=clamp_mode,
                         pad_value=pad_value)
