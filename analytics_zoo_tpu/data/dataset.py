"""TPUDataset — the TFDataset-equivalent bridge from data to device batches.

Mirrors the contract of `pyzoo/zoo/tfpark/tf_dataset.py:115-173` exactly:
training takes a *global* `batch_size` that must divide by the total
data-parallel size; inference/eval take per-device `batch_per_thread`;
setting both is an error. `hard_code_batch_size` semantics are the default
here — TPU programs want static shapes, so training batches are always
whole (`drop_remainder`) and eval tails compile a second (smaller) program.

Sources: ndarrays, XShards of {"x": ..., "y": ...}, pandas DataFrames
(feature/label columns, the `to_dataset` path of
`orca/learn/tf/estimator.py:225-276`), and python generators.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from analytics_zoo_tpu.data.shards import XShards


class TPUDataset:
    """Feed abstraction carrying (x, y) numpy structures + batching rules."""

    def __init__(self, x, y=None, batch_size: int = -1,
                 batch_per_thread: int = -1, shuffle: bool = True):
        if batch_size != -1 and batch_per_thread != -1:
            raise ValueError(
                "bath_size and batch_per_thread should not be set simultaneously"
            )  # message mirrors tf_dataset.py:134
        self.x, self.y = x, y
        self.batch_size = batch_size
        self.batch_per_thread = batch_per_thread
        self.shuffle = shuffle
        self.val: Optional["TPUDataset"] = None  # optional validation split

    # -- constructors (`TFDataset.from_*`) ---------------------------------
    @staticmethod
    def from_ndarrays(tensors, batch_size: int = -1,
                      batch_per_thread: int = -1, val_tensors=None,
                      shuffle: bool = True) -> "TPUDataset":
        """`TFDataset.from_ndarrays` (`tf_dataset.py:378`): tensors is
        (x, y) or {"x":..., "y":...} or a single x structure."""
        if isinstance(tensors, dict):
            x, y = tensors["x"], tensors.get("y")
        elif isinstance(tensors, (tuple, list)) and len(tensors) == 2:
            x, y = tensors
        else:
            x, y = tensors, None
        ds = TPUDataset(x, y, batch_size, batch_per_thread, shuffle)
        if val_tensors is not None:
            # val inherits the caller's batching (the reference's
            # from_ndarrays carries val through with the same batch), no
            # shuffle
            ds.val = TPUDataset.from_ndarrays(
                val_tensors, batch_size=batch_size,
                batch_per_thread=batch_per_thread, shuffle=False)
        return ds

    @staticmethod
    def from_xshards(shards: XShards, batch_size: int = -1,
                     batch_per_thread: int = -1,
                     shuffle: bool = True) -> "TPUDataset":
        """XShards of {"x": ndarray|tuple, "y": ...} → dataset
        (`to_dataset` XShards path, `orca/learn/tf/utils.py:23-54`)."""
        merged = shards.to_numpy()
        if isinstance(merged, dict):
            x, y = merged["x"], merged.get("y")
        else:
            raise ValueError(
                'XShards for training must hold {"x": ..., "y": ...} dicts; '
                "got " + type(merged).__name__)
        return TPUDataset(x, y, batch_size, batch_per_thread, shuffle)

    @staticmethod
    def from_dataframe(df, feature_cols: Sequence[str],
                       label_cols: Optional[Sequence[str]] = None,
                       batch_size: int = -1, batch_per_thread: int = -1,
                       shuffle: bool = True) -> "TPUDataset":
        """pandas DataFrame + feature/label columns (`to_dataset` DataFrame
        path, `orca/learn/tf/estimator.py:251-265`)."""
        feats = [np.stack(df[c].to_numpy()) for c in feature_cols]
        x = feats[0] if len(feats) == 1 else tuple(feats)
        y = None
        if label_cols:
            labels = [np.stack(df[c].to_numpy()) for c in label_cols]
            y = labels[0] if len(labels) == 1 else tuple(labels)
        return TPUDataset(x, y, batch_size, batch_per_thread, shuffle)

    @staticmethod
    def from_feature_set(fs, batch_size: int = -1,
                         batch_per_thread: int = -1) -> "TPUDataset":
        return fs.to_dataset(batch_size=batch_size,
                             batch_per_thread=batch_per_thread)

    @staticmethod
    def from_tfrecord(paths, parse_fn: Callable[[Dict[str, Any]], Tuple],
                      batch_size: int = -1, batch_per_thread: int = -1,
                      shuffle: bool = True, shuffle_buffer: int = 8192,
                      verify_payload: bool = False,
                      num_workers: int = 1) -> "TPUDataset":
        """Stream a TFRecord corpus into training (the reference's
        `TFDataset.from_tf_data_dataset`/`TFBytesDataset` role,
        `tf_dataset.py:593,911`, minus the tf.data graph shuttling).

        `paths` is a glob pattern, directory, or file list; `parse_fn` maps
        one decoded `tf.train.Example` dict ({name: ndarray | list[bytes]})
        to an (x, y) sample of fixed-shape arrays. Records stream through a
        `shuffle_buffer`-sized shuffle window per epoch (file order is also
        reshuffled per epoch); batches are stacked to static shapes and the
        tail remainder is dropped, per the training batch contract.

        `num_workers` > 1 runs decode+parse through the threaded
        order-preserving map (`image.parallel_map_ordered`) — JPEG decode
        and cv2 augmentation release the GIL, so an ImageNet-style
        pipeline keeps the chip fed."""
        from analytics_zoo_tpu.data import tfrecord as tfr
        files = tfr.expand_files(paths)
        return _TFRecordDataset(files, parse_fn, batch_size,
                                batch_per_thread, shuffle, shuffle_buffer,
                                verify_payload, num_workers)

    # -- consumption -------------------------------------------------------
    def n_samples(self) -> int:
        import jax
        return len(jax.tree_util.tree_leaves(self.x)[0])

    def materialize(self) -> Tuple[Any, Any]:
        """(x, y) as in-memory arrays — lazy/streaming subclasses override.
        Eval/predict paths run over arrays; training streams."""
        return self.x, self.y

    def global_batch(self, data_parallel: int) -> int:
        """Resolve the per-step global batch, enforcing the reference's
        divisibility contract (`tf_dataset.py:142-147`)."""
        if self.batch_size != -1:
            if self.batch_size % data_parallel:
                raise ValueError(
                    f"batch_size ({self.batch_size}) must be a multiple of "
                    f"the data-parallel size ({data_parallel})")
            return self.batch_size
        per = self.batch_per_thread if self.batch_per_thread != -1 else 32
        return per * data_parallel

    def iter_train(self, data_parallel: int, seed: int = 0):
        from analytics_zoo_tpu.learn.trainer import iter_batches
        batch = self.global_batch(data_parallel)
        return iter_batches(self.x, self.y, batch, shuffle=self.shuffle,
                            seed=seed, drop_remainder=True)

    def __repr__(self):
        return (f"TPUDataset(n={self.n_samples()}, "
                f"batch_size={self.batch_size}, "
                f"batch_per_thread={self.batch_per_thread})")


class _FeatureSetDataset(TPUDataset):
    """Lazy bridge over a disk-tier FeatureSet: batches gather from the
    memmapped store per step instead of materializing the whole set."""

    def __init__(self, fs, batch_size: int = -1, batch_per_thread: int = -1):
        super().__init__(x=None, y=None, batch_size=batch_size,
                         batch_per_thread=batch_per_thread)
        self._fs = fs

    def n_samples(self) -> int:
        return len(self._fs)

    def materialize(self):
        merged = self._fs.take(np.arange(len(self._fs)))
        if isinstance(merged, dict) and "x" in merged:
            return merged["x"], merged.get("y")
        return merged, None

    def iter_train(self, data_parallel: int, seed: int = 0):
        batch = self.global_batch(data_parallel)
        for b in self._fs.iter_batches(batch, shuffle=self.shuffle,
                                       seed=seed):
            if isinstance(b, dict) and "x" in b:
                yield b["x"], b.get("y"), batch
            else:
                yield b, None, batch


class _TFRecordDataset(TPUDataset):
    """Streaming TFRecord corpus → static-shape batches, via a bounded
    shuffle buffer (no full materialization; a corpus larger than host RAM
    trains fine)."""

    def __init__(self, files: List[str], parse_fn, batch_size: int,
                 batch_per_thread: int, shuffle: bool, shuffle_buffer: int,
                 verify_payload: bool, num_workers: int = 1):
        super().__init__(x=None, y=None, batch_size=batch_size,
                         batch_per_thread=batch_per_thread, shuffle=shuffle)
        if parse_fn is None:
            raise ValueError(
                "from_tfrecord needs a parse_fn mapping an Example dict to "
                "an (x, y) sample")
        self._files = files
        self._parse_fn = parse_fn
        self._shuffle_buffer = max(1, shuffle_buffer)
        self._verify_payload = verify_payload
        self._num_workers = max(1, num_workers)
        self._n: Optional[int] = None

    def n_samples(self) -> int:
        if self._n is None:
            from analytics_zoo_tpu.data import tfrecord as tfr
            self._n = sum(tfr.count_records(f) for f in self._files)
        return self._n

    def first_sample(self):
        """Parse just the first record (shape/dtype probe for model build —
        avoids paying a full shuffle-buffer fill for one sample)."""
        from analytics_zoo_tpu.data import tfrecord as tfr
        for path in self._files:
            for payload in tfr.read_records(
                    path, verify_payload=self._verify_payload):
                return self._parse_fn(tfr.decode_example(payload))
        raise ValueError(f"TFRecord corpus is empty: {self._files!r}")

    def materialize(self):
        """Read the whole corpus into stacked arrays (eval/predict path —
        training should stream via iter_train instead)."""
        import jax
        samples = list(self._iter_samples(np.random.RandomState(0),
                                          ordered=True))
        if not samples:
            raise ValueError(f"TFRecord corpus is empty: {self._files!r}")
        xs = [s[0] for s in samples]
        ys = [s[1] for s in samples]
        x = jax.tree_util.tree_map(lambda *a: np.stack(a), *xs)
        y = None if ys[0] is None \
            else jax.tree_util.tree_map(lambda *a: np.stack(a), *ys)
        return x, y

    def _iter_samples(self, rng: np.random.RandomState,
                      ordered: bool = False):
        from analytics_zoo_tpu.data import tfrecord as tfr
        from analytics_zoo_tpu.data.image import parallel_map_ordered
        files = list(self._files)
        if self.shuffle and not ordered:
            rng.shuffle(files)

        def payloads():
            for path in files:
                yield from tfr.read_records(
                    path, verify_payload=self._verify_payload)

        yield from parallel_map_ordered(
            lambda p: self._parse_fn(tfr.decode_example(p)),
            payloads(), self._num_workers)

    def iter_train(self, data_parallel: int, seed: int = 0):
        import jax
        batch = self.global_batch(data_parallel)
        rng = np.random.RandomState(seed)

        def stack(samples):
            xs = [s[0] for s in samples]
            ys = [s[1] for s in samples]
            xb = jax.tree_util.tree_map(lambda *a: np.stack(a), *xs)
            yb = None if ys[0] is None \
                else jax.tree_util.tree_map(lambda *a: np.stack(a), *ys)
            return xb, yb, batch

        buf: List[Tuple] = []
        pending: List[Tuple] = []
        for sample in self._iter_samples(rng):
            if self.shuffle:
                buf.append(sample)
                if len(buf) < self._shuffle_buffer:
                    continue
                i = rng.randint(len(buf))
                buf[i], sample = buf[-1], buf[i]
                buf.pop()
            pending.append(sample)
            if len(pending) == batch:
                yield stack(pending)
                pending = []
        # drain the shuffle window; drop the tail remainder (static shapes)
        if self.shuffle and buf:
            rng.shuffle(buf)
            for sample in buf:
                pending.append(sample)
                if len(pending) == batch:
                    yield stack(pending)
                    pending = []
