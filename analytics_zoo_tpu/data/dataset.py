"""TPUDataset — the TFDataset-equivalent bridge from data to device batches.

Mirrors the contract of `pyzoo/zoo/tfpark/tf_dataset.py:115-173` exactly:
training takes a *global* `batch_size` that must divide by the total
data-parallel size; inference/eval take per-device `batch_per_thread`;
setting both is an error. `hard_code_batch_size` semantics are the default
here — TPU programs want static shapes, so training batches are always
whole (`drop_remainder`) and eval tails compile a second (smaller) program.

Sources: ndarrays, XShards of {"x": ..., "y": ...}, pandas DataFrames
(feature/label columns, the `to_dataset` path of
`orca/learn/tf/estimator.py:225-276`), and python generators.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from analytics_zoo_tpu.data.shards import XShards


class TPUDataset:
    """Feed abstraction carrying (x, y) numpy structures + batching rules."""

    def __init__(self, x, y=None, batch_size: int = -1,
                 batch_per_thread: int = -1, shuffle: bool = True):
        if batch_size != -1 and batch_per_thread != -1:
            raise ValueError(
                "bath_size and batch_per_thread should not be set simultaneously"
            )  # message mirrors tf_dataset.py:134
        self.x, self.y = x, y
        self.batch_size = batch_size
        self.batch_per_thread = batch_per_thread
        self.shuffle = shuffle
        self.val: Optional["TPUDataset"] = None  # optional validation split

    # -- constructors (`TFDataset.from_*`) ---------------------------------
    @staticmethod
    def from_ndarrays(tensors, batch_size: int = -1,
                      batch_per_thread: int = -1, val_tensors=None,
                      shuffle: bool = True) -> "TPUDataset":
        """`TFDataset.from_ndarrays` (`tf_dataset.py:378`): tensors is
        (x, y) or {"x":..., "y":...} or a single x structure."""
        if isinstance(tensors, dict):
            x, y = tensors["x"], tensors.get("y")
        elif isinstance(tensors, (tuple, list)) and len(tensors) == 2:
            x, y = tensors
        else:
            x, y = tensors, None
        ds = TPUDataset(x, y, batch_size, batch_per_thread, shuffle)
        if val_tensors is not None:
            # val inherits the caller's batching (the reference's
            # from_ndarrays carries val through with the same batch), no
            # shuffle
            ds.val = TPUDataset.from_ndarrays(
                val_tensors, batch_size=batch_size,
                batch_per_thread=batch_per_thread, shuffle=False)
        return ds

    @staticmethod
    def from_xshards(shards: XShards, batch_size: int = -1,
                     batch_per_thread: int = -1,
                     shuffle: bool = True) -> "TPUDataset":
        """XShards of {"x": ndarray|tuple, "y": ...} → dataset
        (`to_dataset` XShards path, `orca/learn/tf/utils.py:23-54`)."""
        merged = shards.to_numpy()
        if isinstance(merged, dict):
            x, y = merged["x"], merged.get("y")
        else:
            raise ValueError(
                'XShards for training must hold {"x": ..., "y": ...} dicts; '
                "got " + type(merged).__name__)
        return TPUDataset(x, y, batch_size, batch_per_thread, shuffle)

    @staticmethod
    def from_dataframe(df, feature_cols: Sequence[str],
                       label_cols: Optional[Sequence[str]] = None,
                       batch_size: int = -1, batch_per_thread: int = -1,
                       shuffle: bool = True) -> "TPUDataset":
        """pandas DataFrame + feature/label columns (`to_dataset` DataFrame
        path, `orca/learn/tf/estimator.py:251-265`)."""
        feats = [np.stack(df[c].to_numpy()) for c in feature_cols]
        x = feats[0] if len(feats) == 1 else tuple(feats)
        y = None
        if label_cols:
            labels = [np.stack(df[c].to_numpy()) for c in label_cols]
            y = labels[0] if len(labels) == 1 else tuple(labels)
        return TPUDataset(x, y, batch_size, batch_per_thread, shuffle)

    @staticmethod
    def from_feature_set(fs, batch_size: int = -1,
                         batch_per_thread: int = -1) -> "TPUDataset":
        return fs.to_dataset(batch_size=batch_size,
                             batch_per_thread=batch_per_thread)

    @staticmethod
    def from_tfrecord(paths, parse_fn: Callable[[Dict[str, Any]], Tuple],
                      batch_size: int = -1, batch_per_thread: int = -1,
                      shuffle: bool = True, shuffle_buffer: int = 8192,
                      verify_payload: bool = False,
                      num_workers: Optional[int] = None,
                      pipeline_workers: Optional[int] = None
                      ) -> "TPUDataset":
        """Stream a TFRecord corpus into training (the reference's
        `TFDataset.from_tf_data_dataset`/`TFBytesDataset` role,
        `tf_dataset.py:593,911`, minus the tf.data graph shuttling).

        `paths` is a glob pattern, directory, or file list; `parse_fn` maps
        one decoded `tf.train.Example` dict ({name: ndarray | list[bytes]})
        to an (x, y) sample of fixed-shape arrays. Records stream through a
        `shuffle_buffer`-sized shuffle window per epoch (file order is also
        reshuffled per epoch); batches are stacked to static shapes and the
        tail remainder is dropped, per the training batch contract.

        `pipeline_workers` (default: `ZooConfig.pipeline_workers` /
        env ZOO_PIPELINE_WORKERS, else `num_workers`) runs read+decode
        through the parallel shard pipeline (`data/pipeline.py`): each
        FILE is decoded on a worker thread — frame batches through the
        vectorized `decode_example_batch`, then `parse_fn` per sample —
        and a bounded reorder buffer re-serializes shard order, so the
        batch stream is bitwise-identical at any worker count (a pure
        function of `(seed, epoch)`). Multi-host fits automatically
        read DISJOINT files per host (`pipeline.host_shard` over the
        mesh's data axis). `num_workers` is the legacy spelling of the
        same knob: when passed (any value, including an explicit 1 to
        opt out of decode threads) it wins over ambient config, and
        `pipeline_workers` wins over both."""
        from analytics_zoo_tpu.data import tfrecord as tfr
        files = tfr.expand_files(paths)
        return _TFRecordDataset(files, parse_fn, batch_size,
                                batch_per_thread, shuffle, shuffle_buffer,
                                verify_payload, num_workers,
                                pipeline_workers)

    # -- consumption -------------------------------------------------------
    def n_samples(self) -> int:
        import jax
        return len(jax.tree_util.tree_leaves(self.x)[0])

    def materialize(self) -> Tuple[Any, Any]:
        """(x, y) as in-memory arrays — lazy/streaming subclasses override.
        Eval/predict paths run over arrays; training streams."""
        return self.x, self.y

    def global_batch(self, data_parallel: int) -> int:
        """Resolve the per-step global batch, enforcing the reference's
        divisibility contract (`tf_dataset.py:142-147`)."""
        if self.batch_size != -1:
            if self.batch_size % data_parallel:
                raise ValueError(
                    f"batch_size ({self.batch_size}) must be a multiple of "
                    f"the data-parallel size ({data_parallel})")
            return self.batch_size
        per = self.batch_per_thread if self.batch_per_thread != -1 else 32
        return per * data_parallel

    def iter_train(self, data_parallel: int, seed: int = 0):
        from analytics_zoo_tpu.learn.trainer import iter_batches
        batch = self.global_batch(data_parallel)
        return iter_batches(self.x, self.y, batch, shuffle=self.shuffle,
                            seed=seed, drop_remainder=True)

    def __repr__(self):
        return (f"TPUDataset(n={self.n_samples()}, "
                f"batch_size={self.batch_size}, "
                f"batch_per_thread={self.batch_per_thread})")


class _FeatureSetDataset(TPUDataset):
    """Lazy bridge over a disk-tier FeatureSet: batches gather from the
    memmapped store per step instead of materializing the whole set."""

    def __init__(self, fs, batch_size: int = -1, batch_per_thread: int = -1):
        super().__init__(x=None, y=None, batch_size=batch_size,
                         batch_per_thread=batch_per_thread)
        self._fs = fs

    def n_samples(self) -> int:
        return len(self._fs)

    def materialize(self):
        merged = self._fs.take(np.arange(len(self._fs)))
        if isinstance(merged, dict) and "x" in merged:
            return merged["x"], merged.get("y")
        return merged, None

    def iter_train(self, data_parallel: int, seed: int = 0):
        batch = self.global_batch(data_parallel)
        for b in self._fs.iter_batches(batch, shuffle=self.shuffle,
                                       seed=seed):
            if isinstance(b, dict) and "x" in b:
                yield b["x"], b.get("y"), batch
            else:
                yield b, None, batch


class _TFRecordDataset(TPUDataset):
    """Streaming TFRecord corpus → static-shape batches, via a bounded
    shuffle buffer (no full materialization; a corpus larger than host RAM
    trains fine). Read+decode runs through the parallel shard pipeline
    (`data/pipeline.py`): files decode concurrently, the reorder buffer
    keeps the sample stream a pure function of `(seed, epoch)`."""

    # multi-host fits read disjoint files per host (iter_train), so the
    # trainer's streaming-duplication guard does not apply
    shards_per_host = True

    # frame batch per vectorized decode_example_batch call
    _DECODE_CHUNK = 256
    # records per pipeline shard: big files split into bounded record
    # ranges, so a worker's residency is ≤ this many parsed samples no
    # matter the file size (a one-file 100 GB corpus still streams)
    _SHARD_RECORDS = 1024

    def __init__(self, files: List[str], parse_fn, batch_size: int,
                 batch_per_thread: int, shuffle: bool, shuffle_buffer: int,
                 verify_payload: bool, num_workers: Optional[int] = None,
                 pipeline_workers: Optional[int] = None):
        super().__init__(x=None, y=None, batch_size=batch_size,
                         batch_per_thread=batch_per_thread, shuffle=shuffle)
        if parse_fn is None:
            raise ValueError(
                "from_tfrecord needs a parse_fn mapping an Example dict to "
                "an (x, y) sample")
        self._files = files
        self._parse_fn = parse_fn
        self._shuffle_buffer = max(1, shuffle_buffer)
        self._verify_payload = verify_payload
        self._num_workers = num_workers
        self._pipeline_workers = pipeline_workers
        self._n: Optional[int] = None
        self._index_cache: Dict[str, Tuple] = {}
        self._count_cache: Dict[str, int] = {}

    def _workers(self) -> int:
        from analytics_zoo_tpu.data.pipeline import resolve_workers
        if self._pipeline_workers is None and self._num_workers is not None:
            # an explicitly-passed legacy num_workers is a call-site
            # decision — INCLUDING num_workers=1 (opting out of decode
            # threads on a co-tenant host): ambient config must not
            # silently override it
            return max(1, self._num_workers)
        return resolve_workers(self._pipeline_workers)

    def _file_index(self, path: str):
        """(payload_offsets, payload_lengths) for one file, memoized —
        the file set is immutable, so the header walk is paid once per
        file per dataset, not per epoch (a fuse-mounted corpus must not
        re-scan every shard at every epoch start)."""
        idx = self._index_cache.get(path)
        if idx is None:
            from analytics_zoo_tpu.data import tfrecord as tfr
            idx = self._index_cache[path] = tfr.scan_index(
                path, verify_payload=self._verify_payload)
        return idx

    def _file_indexes(self, files: List[str]):
        """Memoized indexes for `files`, the uncached ones scanned on
        the worker pool."""
        from analytics_zoo_tpu.data.pipeline import parallel_read
        missing = [f for f in files if f not in self._index_cache]
        if missing:
            parallel_read(missing, self._file_index,
                          workers=self._workers())
        return {f: self._file_index(f) for f in files}

    def _file_count(self, path: str) -> int:
        """Record count for one file, memoized. Reads the index cache
        when the parallel path already built it, else the O(1)-memory
        native/header count — counting must NOT grow a per-record
        index the single-threaded path never needs."""
        idx = self._index_cache.get(path)
        if idx is not None:
            return len(idx[0])
        n = self._count_cache.get(path)
        if n is None:
            from analytics_zoo_tpu.data import tfrecord as tfr
            n = self._count_cache[path] = tfr.count_records(path)
        return n

    def n_samples(self) -> int:
        if self._n is None:
            from analytics_zoo_tpu.data.pipeline import parallel_read
            self._n = sum(parallel_read(self._files, self._file_count,
                                        workers=self._workers()))
        return self._n

    def first_sample(self):
        """Parse just the first record (shape/dtype probe for model build —
        avoids paying a full shuffle-buffer fill for one sample)."""
        from analytics_zoo_tpu.data import tfrecord as tfr
        for path in self._files:
            for payload in tfr.read_records(
                    path, verify_payload=self._verify_payload):
                return self._parse_fn(tfr.decode_example(payload))
        raise ValueError(f"TFRecord corpus is empty: {self._files!r}")

    def materialize(self):
        """Read the whole corpus into stacked arrays (eval/predict path —
        training should stream via iter_train instead)."""
        import jax
        samples = list(self._iter_samples(np.random.RandomState(0),
                                          ordered=True))
        if not samples:
            raise ValueError(f"TFRecord corpus is empty: {self._files!r}")
        xs = [s[0] for s in samples]
        ys = [s[1] for s in samples]
        x = jax.tree_util.tree_map(lambda *a: np.stack(a), *xs)
        y = None if ys[0] is None \
            else jax.tree_util.tree_map(lambda *a: np.stack(a), *ys)
        return x, y

    def _shard_chunks(self, path: str):
        """ONE file's samples, a decode-chunk at a time: frames batch
        through the vectorized Example codec, `parse_fn` runs per
        sample. Yields lists of up to `_DECODE_CHUNK` samples."""
        from analytics_zoo_tpu.data import tfrecord as tfr
        chunk: List[bytes] = []
        for payload in tfr.read_records(
                path, verify_payload=self._verify_payload):
            chunk.append(payload)
            if len(chunk) >= self._DECODE_CHUNK:
                yield [self._parse_fn(ex)
                       for ex in tfr.decode_example_batch(chunk)]
                chunk = []
        if chunk:
            yield [self._parse_fn(ex)
                   for ex in tfr.decode_example_batch(chunk)]

    def _read_shard(self, shard: Tuple[str, int]) -> List[Tuple]:
        """Worker unit for the PARALLEL path: ONE bounded record range
        of one file — seek-read via the memoized index, chunked
        vectorized decode, `parse_fn` per sample. Residency per
        in-flight shard is ≤ `_SHARD_RECORDS` parsed samples no matter
        how big the file is."""
        from analytics_zoo_tpu.data import tfrecord as tfr
        path, start = shard
        offs, lens = self._file_index(path)
        sl = slice(start, start + self._SHARD_RECORDS)
        out: List[Tuple] = []
        chunk: List[bytes] = []
        for payload in tfr.read_payloads_at(path, offs[sl], lens[sl]):
            chunk.append(payload)
            if len(chunk) >= self._DECODE_CHUNK:
                out.extend(self._parse_fn(ex)
                           for ex in tfr.decode_example_batch(chunk))
                chunk = []
        if chunk:
            out.extend(self._parse_fn(ex)
                       for ex in tfr.decode_example_batch(chunk))
        return out

    def _iter_samples(self, rng: np.random.RandomState,
                      ordered: bool = False,
                      files: Optional[List[str]] = None):
        """Sample stream in deterministic shard order: `files` (or the
        per-epoch shuffled file list) read+decoded by the worker pool,
        re-serialized by the reorder buffer — bitwise-identical at any
        worker count. workers<=1 streams chunk-by-chunk (one decode
        chunk resident — a corpus stored as one giant file still
        trains in bounded memory, the class's original contract);
        workers>1 splits every file into `_SHARD_RECORDS`-record
        ranges via the memoized header index, so residency is
        (workers+1) × bounded ranges, never whole files."""
        from analytics_zoo_tpu.data.pipeline import ShardPipeline
        if files is None:
            files = list(self._files)
            if self.shuffle and not ordered:
                rng.shuffle(files)
        workers = self._workers()
        if workers <= 1:
            for path in files:
                for chunk in self._shard_chunks(path):
                    yield from chunk
            return
        indexes = self._file_indexes(files)
        shards = [(path, start)
                  for path in files
                  for start in range(0, len(indexes[path][0]),
                                     self._SHARD_RECORDS)]
        pipe = ShardPipeline(shards, self._read_shard, workers=workers,
                             label_fn=lambda s: s[0])
        try:
            yield from pipe.samples()
        finally:
            pipe.close()

    def _host_files(self, files: List[str]) -> List[str]:
        """Disjoint per-host file assignment for multi-process fits —
        each host streams only its stride of the (seed, epoch)-shuffled
        list, over the mesh's data axis."""
        import jax
        if jax.process_count() <= 1:
            return files
        from analytics_zoo_tpu.data.pipeline import host_shard
        return host_shard(files)

    def iter_train(self, data_parallel: int, seed: int = 0):
        import jax
        batch = self.global_batch(data_parallel)
        n_proc = jax.process_count()
        if n_proc > 1:
            # the GLOBAL batch splits across hosts; each host stacks its
            # LOCAL share from its own disjoint file stride
            if batch % n_proc:
                raise ValueError(
                    f"global batch_size ({batch}) must divide by the "
                    f"process count ({n_proc}) to stream per-host "
                    "TFRecord shards")
            batch //= n_proc
        rng = np.random.RandomState(seed)

        def stack(samples):
            xs = [s[0] for s in samples]
            ys = [s[1] for s in samples]
            xb = jax.tree_util.tree_map(lambda *a: np.stack(a), *xs)
            yb = None if ys[0] is None \
                else jax.tree_util.tree_map(lambda *a: np.stack(a), *ys)
            return xb, yb, batch

        files = list(self._files)
        if self.shuffle:
            rng.shuffle(files)
        files = self._host_files(files)
        max_batches = None
        if n_proc > 1:
            # equalize STEPS across hosts: per-host file strides rarely
            # hold identical record counts, and an uneven epoch would
            # desync the per-step collectives and deadlock mid-epoch —
            # the exact failure the in-memory path guards with its own
            # allgather (trainer.fit_keras). Counts come from the
            # memoized header index, so only the FIRST epoch pays the
            # scan (a fuse-mounted corpus must not re-walk every shard
            # per epoch).
            from jax.experimental import multihost_utils
            from analytics_zoo_tpu.data.pipeline import parallel_read
            local_n = sum(parallel_read(files, self._file_count,
                                        workers=self._workers()))
            counts = np.asarray(multihost_utils.process_allgather(
                np.asarray(local_n, np.int64)))
            max_batches = int(counts.min()) // batch
            if max_batches == 0:
                raise ValueError(
                    "Multi-host TFRecord fit: the smallest host shard "
                    f"holds {int(counts.min())} records, fewer than "
                    f"the per-host batch ({batch}); add shard files "
                    "or lower batch_size")

        def batches():
            buf: List[Tuple] = []
            pending: List[Tuple] = []
            for sample in self._iter_samples(rng, files=files):
                if self.shuffle:
                    buf.append(sample)
                    if len(buf) < self._shuffle_buffer:
                        continue
                    i = rng.randint(len(buf))
                    buf[i], sample = buf[-1], buf[i]
                    buf.pop()
                pending.append(sample)
                if len(pending) == batch:
                    yield stack(pending)
                    pending = []
            # drain the shuffle window; drop the tail remainder (static
            # shapes)
            if self.shuffle and buf:
                rng.shuffle(buf)
                for sample in buf:
                    pending.append(sample)
                    if len(pending) == batch:
                        yield stack(pending)
                        pending = []

        if max_batches is None:
            yield from batches()
            return
        import itertools
        it = batches()
        try:
            # every host emits EXACTLY min-host batches per epoch
            yield from itertools.islice(it, max_batches)
        finally:
            it.close()       # unwinds the shard pipeline's pool
