"""Bbox-aware (roi) image augmentation for detection training.

Reference: the SSD training pipeline chains roi transforms that keep the
ground-truth boxes consistent with every geometric image op
(`feature/image/roi/RoiRecordToFeature.scala`, `ssd/SSDDataSet.scala`:
``ImageRoiNormalize -> ImageExpand -> ImageRoiProject ->
ImageRandomSampler -> ImageResize -> ImageHFlip -> ImageRoiHFlip``; the
box-projection math lives in BigDL's roi label transformers and
`common/BboxUtil.scala`). Here each transform owns both the pixel op and
the box remap in one step — there is no separate "project" pass to forget.

All transforms are host-side numpy (augmentation is input-pipeline work;
the TPU sees only the final fixed-shape batch). Boxes are corner-form
``[x1, y1, x2, y2]``; after `RoiNormalize` they are normalized to [0, 1]
which is what the samplers/flip/resize below expect (matching the
reference pipeline order).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

import numpy as np


@dataclass
class RoiLabel:
    """Ground-truth for one image: integer class per box (0 = background is
    never a gt class), VOC `difficult` flags, corner boxes. The reference's
    `RoiLabel(classes, bboxes)` with the (class, difficult) rows folded into
    typed fields."""

    classes: np.ndarray                       # [G] int32
    boxes: np.ndarray                         # [G, 4] float32 corner
    difficult: np.ndarray = field(default=None)  # [G] float32 0/1

    def __post_init__(self):
        self.classes = np.asarray(self.classes, np.int32).reshape(-1)
        self.boxes = np.asarray(self.boxes, np.float32).reshape(-1, 4)
        if self.difficult is None:
            self.difficult = np.zeros(len(self.classes), np.float32)
        else:
            self.difficult = np.asarray(
                self.difficult, np.float32).reshape(-1)
        if not (len(self.classes) == len(self.boxes)
                == len(self.difficult)):
            raise ValueError("classes/boxes/difficult length mismatch")

    def __len__(self):
        return len(self.classes)

    def select(self, mask: np.ndarray) -> "RoiLabel":
        return RoiLabel(self.classes[mask], self.boxes[mask],
                        self.difficult[mask])


class RoiImageProcessing:
    """Composable transform over an ``(image, RoiLabel)`` pair; `>>`
    chains (the reference's `->` operator over roi pipelines)."""

    def apply(self, img: np.ndarray, roi: RoiLabel
              ) -> Tuple[np.ndarray, RoiLabel]:
        raise NotImplementedError

    def __call__(self, feature):
        img, roi = feature
        return self.apply(img, roi)

    def __rshift__(self, other: "RoiImageProcessing") -> "RoiChain":
        return RoiChain([self, other])


class RoiChain(RoiImageProcessing):
    def __init__(self, transforms: Sequence[RoiImageProcessing]):
        self.transforms = list(transforms)

    def apply(self, img, roi):
        for t in self.transforms:
            img, roi = t.apply(img, roi)
        return img, roi

    def __rshift__(self, other):
        return RoiChain(self.transforms + [other])


class RoiLift(RoiImageProcessing):
    """Lift a geometry-preserving image-only op (color jitter, normalize,
    dtype) into a roi chain. Using this with a geometric op would silently
    desync the boxes — that is exactly the bug class the roi transforms
    exist to prevent, so only lift photometric ops."""

    def __init__(self, image_op):
        self.image_op = image_op

    def apply(self, img, roi):
        return self.image_op(img), roi


class RoiRandomPreprocessing(RoiImageProcessing):
    """Apply the wrapped roi transform with probability p
    (`ImageRandomPreprocessing` around Expand/HFlip in the SSD chain)."""

    def __init__(self, transform: RoiImageProcessing, p: float = 0.5,
                 seed: Optional[int] = None):
        self.transform = transform
        self.p = p
        self.rng = np.random.RandomState(seed)

    def apply(self, img, roi):
        if self.rng.rand() < self.p:
            return self.transform.apply(img, roi)
        return img, roi


class RoiNormalize(RoiImageProcessing):
    """Pixel-coordinate boxes -> [0, 1] normalized (`ImageRoiNormalize`).
    Every transform below this point works in normalized space."""

    def apply(self, img, roi):
        H, W = img.shape[:2]
        scale = np.array([W, H, W, H], np.float32)
        return img, RoiLabel(roi.classes, roi.boxes / scale, roi.difficult)


class RoiHFlip(RoiImageProcessing):
    """Mirror image + boxes: x1' = 1-x2, x2' = 1-x1 (`ImageHFlip` +
    `ImageRoiHFlip`). Boxes must be normalized."""

    def apply(self, img, roi):
        flipped = img[:, ::-1].copy()
        b = roi.boxes
        nb = np.stack([1.0 - b[:, 2], b[:, 1], 1.0 - b[:, 0], b[:, 3]],
                      axis=1) if len(roi) else b
        return flipped, RoiLabel(roi.classes, nb, roi.difficult)


class RoiResize(RoiImageProcessing):
    """Resize the pixels; normalized boxes are scale-invariant so they pass
    through untouched (`ImageResize` inside the roi chain)."""

    def __init__(self, resize_h: int, resize_w: int):
        self.h, self.w = resize_h, resize_w

    def apply(self, img, roi):
        import cv2
        img = cv2.resize(img, (self.w, self.h),
                         interpolation=cv2.INTER_LINEAR)
        return img, roi


class RoiExpand(RoiImageProcessing):
    """SSD "zoom-out": paste the image at a random offset inside a canvas
    of ratio r ∈ [1, max_expand_ratio] filled with the channel means, then
    shrink the normalized boxes into the canvas frame (`ImageExpand` +
    `ImageRoiProject`). Trains small-object detection."""

    def __init__(self, max_expand_ratio: float = 4.0,
                 means: Sequence[float] = (123.0, 117.0, 104.0),
                 seed: Optional[int] = None):
        self.max_ratio = max_expand_ratio
        self.means = np.asarray(means, np.float32)
        self.rng = np.random.RandomState(seed)

    def apply(self, img, roi):
        H, W = img.shape[:2]
        r = self.rng.uniform(1.0, self.max_ratio)
        nH, nW = int(round(H * r)), int(round(W * r))
        y0 = int(self.rng.uniform(0, nH - H + 1))
        x0 = int(self.rng.uniform(0, nW - W + 1))
        canvas = np.empty((nH, nW, img.shape[2]), img.dtype)
        canvas[...] = self.means.astype(img.dtype)
        canvas[y0:y0 + H, x0:x0 + W] = img
        if len(roi):
            sx, sy = W / nW, H / nH
            ox, oy = x0 / nW, y0 / nH
            b = roi.boxes * np.array([sx, sy, sx, sy], np.float32) \
                + np.array([ox, oy, ox, oy], np.float32)
            roi = RoiLabel(roi.classes, b, roi.difficult)
        return canvas, roi


def _crop_iou(crop: np.ndarray, boxes: np.ndarray) -> np.ndarray:
    """Jaccard of one normalized crop rect vs [G,4] boxes."""
    ix1 = np.maximum(crop[0], boxes[:, 0])
    iy1 = np.maximum(crop[1], boxes[:, 1])
    ix2 = np.minimum(crop[2], boxes[:, 2])
    iy2 = np.minimum(crop[3], boxes[:, 3])
    inter = np.clip(ix2 - ix1, 0, None) * np.clip(iy2 - iy1, 0, None)
    area_c = (crop[2] - crop[0]) * (crop[3] - crop[1])
    area_b = np.clip(boxes[:, 2] - boxes[:, 0], 0, None) \
        * np.clip(boxes[:, 3] - boxes[:, 1], 0, None)
    return inter / np.maximum(area_c + area_b - inter, 1e-8)


def project_boxes(roi: RoiLabel, crop: np.ndarray) -> RoiLabel:
    """Remap normalized boxes into a normalized crop rect: keep gts whose
    CENTER falls inside the crop, translate + rescale, clip to [0, 1]
    (the reference sampler's `ImageRoiProject` center rule)."""
    if not len(roi):
        return roi
    b = roi.boxes
    cx = (b[:, 0] + b[:, 2]) / 2
    cy = (b[:, 1] + b[:, 3]) / 2
    keep = ((cx > crop[0]) & (cx < crop[2])
            & (cy > crop[1]) & (cy < crop[3]))
    kept = roi.select(keep)
    if not len(kept):
        return kept
    cw, ch = crop[2] - crop[0], crop[3] - crop[1]
    nb = (kept.boxes - np.array([crop[0], crop[1], crop[0], crop[1]],
                                np.float32)) \
        / np.array([cw, ch, cw, ch], np.float32)
    return RoiLabel(kept.classes, np.clip(nb, 0.0, 1.0), kept.difficult)


class RoiRandomSampler(RoiImageProcessing):
    """The SSD batch sampler (`ImageRandomSampler`): alongside the whole
    image, try up to `max_trials` random crops per min-IoU constraint in
    `min_overlaps` (scale ∈ [min_scale, 1], aspect ∈ [min/max_aspect],
    accepted when some gt box reaches the IoU floor); pick one of the
    accepted crops uniformly and project the boxes into it."""

    def __init__(self,
                 min_overlaps: Sequence[float] = (0.1, 0.3, 0.5, 0.7, 0.9),
                 min_scale: float = 0.3,
                 min_aspect: float = 0.5, max_aspect: float = 2.0,
                 max_trials: int = 50, max_sample: int = 1,
                 seed: Optional[int] = None):
        self.min_overlaps = tuple(min_overlaps)
        self.min_scale = min_scale
        self.min_aspect, self.max_aspect = min_aspect, max_aspect
        self.max_trials = max_trials
        self.max_sample = max_sample
        self.rng = np.random.RandomState(seed)

    def _sample_crop(self) -> np.ndarray:
        scale = self.rng.uniform(self.min_scale, 1.0)
        # keep the crop inside the unit square: ar bounded by scale²
        lo = max(self.min_aspect, scale * scale)
        hi = min(self.max_aspect, 1.0 / (scale * scale))
        ar = self.rng.uniform(lo, hi)
        w = scale * np.sqrt(ar)
        h = scale / np.sqrt(ar)
        x0 = self.rng.uniform(0.0, 1.0 - w)
        y0 = self.rng.uniform(0.0, 1.0 - h)
        return np.array([x0, y0, x0 + w, y0 + h], np.float32)

    def apply(self, img, roi):
        crops = [np.array([0.0, 0.0, 1.0, 1.0], np.float32)]
        for min_iou in self.min_overlaps:
            found = 0
            for _ in range(self.max_trials):
                if found >= self.max_sample:
                    break
                crop = self._sample_crop()
                if len(roi) == 0:
                    continue
                if _crop_iou(crop, roi.boxes).max() >= min_iou:
                    # only crops that keep at least one gt center are
                    # usable for training
                    if len(project_boxes(roi, crop)):
                        crops.append(crop)
                        found += 1
        crop = crops[self.rng.randint(len(crops))]
        if np.allclose(crop, [0.0, 0.0, 1.0, 1.0]):
            return img, roi
        H, W = img.shape[:2]
        x0, y0 = int(crop[0] * W), int(crop[1] * H)
        x1, y1 = max(x0 + 1, int(crop[2] * W)), max(y0 + 1, int(crop[3] * H))
        return img[y0:y1, x0:x1].copy(), project_boxes(roi, crop)


def ssd_train_transforms(resolution: int,
                         means: Sequence[float] = (123.0, 117.0, 104.0),
                         expand_p: float = 0.5, flip_p: float = 0.5,
                         seed: Optional[int] = None,
                         color_jitter="default") -> RoiChain:
    """The reference SSD training chain (`SSDDataSet.loadSSDTrainSet`):
    normalize rois -> color jitter -> random expand -> random IoU crop ->
    resize -> random hflip. `color_jitter=None` disables the photometric
    leg; channel normalization/dtype is left to the caller's lifted ops so
    eval/train share it."""
    rng = np.random.RandomState(seed)

    def sub():          # independent child streams, one seeded source
        return int(rng.randint(0, 2 ** 31 - 1))

    chain: List[RoiImageProcessing] = [RoiNormalize()]
    if color_jitter == "default":
        from analytics_zoo_tpu.data.image import ImageColorJitter
        color_jitter = ImageColorJitter(seed=sub())
    if color_jitter is not None:
        chain.append(RoiLift(color_jitter))
    chain += [
        RoiRandomPreprocessing(RoiExpand(means=means, seed=sub()),
                               p=expand_p, seed=sub()),
        RoiRandomSampler(seed=sub()),
        RoiResize(resolution, resolution),
    ]
    flip = RoiRandomPreprocessing(RoiHFlip(), p=flip_p, seed=sub())
    chain.append(flip)
    return RoiChain(chain)


def ssd_val_transforms(resolution: int) -> RoiChain:
    """Eval chain: normalize + resize only (`loadSSDValSet`)."""
    return RoiChain([RoiNormalize(), RoiResize(resolution, resolution)])
