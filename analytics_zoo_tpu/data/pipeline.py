"""Parallel streaming host input pipeline (ISSUE 15).

Every compute-side lever the platform pulls — sharded pjit fit, fused
Pallas optimizer, shared AOT cache — assumes the accelerator is FED. On
a real TPU a BERT step is milliseconds, so the single-threaded Python
decode the seed shipped (`_TFRecordDataset.iter_train` parsing records
one at a time on the consumer thread) makes any file-backed fit
input-bound. This module is the host-side answer, the training twin of
the serving pipeline (PR 1): a worker pool reads+decodes *shards*
(files / row-groups / index-batches — whatever the dataset's parallel
unit is) concurrently, and a bounded reorder buffer re-serializes the
results so the emitted sample stream is the EXACT shard order the
caller supplied, at any worker count.

Determinism contract: output order is a pure function of the shard
order (which the datasets derive from `(seed, epoch)`), never of
thread scheduling. `pipeline_workers=1` and `=16` produce bitwise-
identical streams — test-asserted in tests/test_input_pipeline.py —
so turning parallelism on cannot change a single training batch.

Memory contract: at most `workers + reorder_slack` decoded shards are
ever resident. Admission is window-gated on the CONSUMER's progress
(a worker may start shard `i` only once shard `i - window` has been
retired), so a slow consumer backpressures the pool instead of the
pool racing ahead and buffering the corpus. A 10 GB corpus streams in
a small fixed host footprint.

Failure contract: a shard that fails to read/decode surfaces ONE
actionable error *naming the shard*, raised at the shard's position in
the stream (deterministic — the same error at any worker count), never
a hang or a silent short epoch.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Callable, Iterator, List, Optional, Sequence

log = logging.getLogger("analytics_zoo_tpu.data.pipeline")

# consumer/worker wakeup granularity; purely an interruption bound
# (shutdown latency), never a throughput knob — all handoffs are
# condition-notified
_WAIT_S = 0.1


def resolve_workers(explicit: Optional[int] = None,
                    default: int = 1) -> int:
    """One resolution rule for every dataset/reader knob: an explicit
    per-call `pipeline_workers` wins; otherwise the context config's
    `pipeline_workers` (env `ZOO_PIPELINE_WORKERS`); otherwise
    `default` (single-threaded — parallelism is opt-in)."""
    if explicit is not None:
        return max(1, int(explicit))
    try:
        from analytics_zoo_tpu.common.context import get_context
        cfg = getattr(get_context(), "config", None)
        w = int(getattr(cfg, "pipeline_workers", 0) or 0)
        if w > 0:
            return w
    except Exception:  # noqa: BLE001 — config is optional here
        pass
    return max(1, int(default))


def host_shard(items: Sequence[Any], index: Optional[int] = None,
               count: Optional[int] = None) -> List[Any]:
    """Deterministic per-host shard assignment over the mesh's data
    axis: host `index` of `count` owns `items[index::count]` — disjoint
    across hosts, union = all items, and a pure function of the item
    order (shuffle first, then assign, and every host's subset is
    reproducible from the same `(seed, epoch)`). Defaults read the JAX
    process topology, under which process order IS the data-axis order
    (`mesh_utils` lays processes out along the outermost axis)."""
    if index is None or count is None:
        import jax
        index = jax.process_index() if index is None else index
        count = jax.process_count() if count is None else count
    if not (0 <= index < count):
        raise ValueError(f"host_shard: index {index} outside [0, {count})")
    mine = list(items[index:: count])
    if not mine:
        raise ValueError(
            f"host_shard: host {index} of {count} gets no shards from "
            f"{len(items)} — a host with nothing to read would desync "
            "the per-step collectives; use fewer hosts or more shards")
    return mine


class _ShardError:
    """A worker's failure, parked at its shard's sequence slot so the
    consumer raises it deterministically in stream order."""

    __slots__ = ("exc", "label")

    def __init__(self, exc: BaseException, label: str):
        self.exc = exc
        self.label = label

    def raise_(self):
        exc = self.exc
        if self.label and self.label in str(exc):
            raise exc          # already names the shard (tfrecord errors)
        try:
            wrapped = type(exc)(f"{self.label}: {exc}")
        except Exception:  # noqa: BLE001 — exotic exception signature
            wrapped = RuntimeError(
                f"{self.label}: {type(exc).__name__}: {exc}")
        raise wrapped from exc


class ShardPipeline:
    """Worker pool over an ordered shard list with a bounded reorder
    buffer: `read_fn(shard)` runs concurrently, `samples()` yields each
    shard's items strictly in the given shard order.

    `label_fn(shard)` names a shard in errors (default `str`); pass the
    file path for file shards. `reorder_slack` is the extra completed
    shards the buffer may hold beyond the in-flight set (1 keeps the
    pool busy across a slow head-of-line shard without unbounding
    memory). `max_resident` records the high-water mark of decoded
    shards held at once — the bounded-memory contract, assertable in
    tests."""

    def __init__(self, shards: Sequence[Any],
                 read_fn: Callable[[Any], Sequence[Any]],
                 workers: int = 4, reorder_slack: int = 1,
                 label_fn: Callable[[Any], str] = str):
        self._shards = list(shards)
        self._read_fn = read_fn
        self._label_fn = label_fn
        self.workers = max(1, min(int(workers), len(self._shards) or 1))
        self._window = self.workers + max(0, int(reorder_slack))
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._done: dict = {}          # seq -> List[sample] | _ShardError
        self._next_submit = 0          # next shard index to hand a worker
        self._next_emit = 0            # next shard index the consumer needs
        self._running = 0              # shards currently being decoded
        self._stop = False
        self.max_resident = 0
        self._threads = [
            threading.Thread(target=self._worker, daemon=True,
                             name=f"input-pipeline-{i}")
            for i in range(self.workers)]
        for t in self._threads:
            t.start()

    # -- worker side -------------------------------------------------------
    def _claim(self) -> Optional[int]:
        """Next shard index this worker may start, respecting the
        admission window; None once the list is exhausted or stopped."""
        with self._cond:
            while not self._stop:
                if self._next_submit >= len(self._shards):
                    return None
                if self._next_submit < self._next_emit + self._window:
                    seq = self._next_submit
                    self._next_submit += 1
                    self._running += 1
                    return seq
                self._cond.wait(_WAIT_S)
            return None

    def _worker(self):
        while True:
            seq = self._claim()
            if seq is None:
                return
            shard = self._shards[seq]
            try:
                out: Any = list(self._read_fn(shard))
            except Exception as e:  # noqa: BLE001 — parked for the consumer
                out = _ShardError(e, self._label_fn(shard))
            with self._cond:
                self._running -= 1
                if self._stop:
                    return
                self._done[seq] = out
                resident = len(self._done) + self._running
                if resident > self.max_resident:
                    self.max_resident = resident
                self._cond.notify_all()

    # -- consumer side -----------------------------------------------------
    def samples(self) -> Iterator[Any]:
        """Yield every shard's items in shard order. A shard error
        raises at that shard's position (items of earlier shards were
        already delivered). Always pairs with `close()` — the generator
        closes the pipeline itself on normal exhaustion, early `break`
        (GeneratorExit) and error alike."""
        try:
            for seq in range(len(self._shards)):
                with self._cond:
                    while seq not in self._done and not self._stop:
                        self._cond.wait(_WAIT_S)
                    if self._stop:
                        return
                    out = self._done.pop(seq)
                    self._next_emit = seq + 1
                    self._cond.notify_all()   # window advanced: admit next
                if isinstance(out, _ShardError):
                    out.raise_()
                yield from out
        finally:
            self.close()

    def close(self):
        """Stop the pool and drop buffered shards; idempotent."""
        with self._cond:
            self._stop = True
            self._done.clear()
            self._cond.notify_all()
        for t in self._threads:
            t.join(timeout=5.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def parallel_read(items: Sequence[Any], read_fn: Callable[[Any], Any],
                  workers: Optional[int] = None,
                  label_fn: Callable[[Any], str] = str) -> List[Any]:
    """Ordered parallel map over whole items (one result per item) —
    the shape `readers.read_csv`-style per-file loads want: N files
    read concurrently, results in file order, a per-file failure raised
    as one error naming the file. `workers` resolves via
    `resolve_workers` (explicit > config > 1); at 1 this degrades to a
    plain loop with the same error contract."""
    items = list(items)
    w = resolve_workers(workers, default=1)
    if w <= 1 or len(items) <= 1:
        out = []
        for it in items:
            try:
                out.append(read_fn(it))
            except Exception as e:  # noqa: BLE001 — re-raised with name
                _ShardError(e, label_fn(it)).raise_()
        return out
    pipe = ShardPipeline(items, lambda it: [read_fn(it)], workers=w,
                         label_fn=label_fn)
    try:
        return list(pipe.samples())
    finally:
        pipe.close()
