"""XShards — the partitioned-data abstraction.

TPU-native analogue of orca's `XShards`/`SparkXShards`
(`pyzoo/zoo/orca/data/shard.py:25,171`): a collection of data shards (pandas
DataFrames, numpy arrays, or `{"x": ..., "y": ...}` dicts) with functional
per-shard transforms. Where the reference partitions across Spark executors,
here shards map to *host input slices* feeding the device mesh: shard i of a
global batch lands on mesh batch-axis slice i (the
`jax.make_array_from_process_local_data` model). On a single host the shards
parallelize preprocessing via a process pool; across hosts each process owns
`len(shards) / process_count` shards.
"""

from __future__ import annotations

import concurrent.futures
import math
import pickle
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import numpy as np


class XShards:
    """A list of in-memory shards with per-shard transforms
    (`shard.py:25` surface: transform_shard/collect/num_partitions)."""

    def __init__(self, shards: Sequence[Any]):
        if not shards:
            raise ValueError("XShards needs at least one shard")
        self.shards: List[Any] = list(shards)

    # -- construction ------------------------------------------------------
    @staticmethod
    def partition(data, num_shards: Optional[int] = None) -> "XShards":
        """Split ndarray / dict-of-ndarray / list into shards
        (`XShards.partition`, `shard.py:40`)."""
        import jax
        n_shards = num_shards or max(jax.process_count(), 1) * 2

        leaves, treedef = jax.tree_util.tree_flatten(data)
        if not leaves:
            raise ValueError("Cannot partition empty data")
        n = len(leaves[0])
        for l in leaves:
            if len(l) != n:
                raise ValueError("All arrays must share the leading dim")
        n_shards = min(n_shards, n)
        bounds = np.linspace(0, n, n_shards + 1, dtype=int)
        shards = []
        for lo, hi in zip(bounds[:-1], bounds[1:]):
            shard_leaves = [np.asarray(l[lo:hi]) for l in leaves]
            shards.append(jax.tree_util.tree_unflatten(treedef, shard_leaves))
        return XShards(shards)

    # -- transforms --------------------------------------------------------
    def transform_shard(self, fn: Callable, *args,
                        parallel: bool = False) -> "XShards":
        """Apply fn to every shard (`SparkXShards.transform_shard`,
        `shard.py:185`). `parallel=True` uses a thread pool (numpy/pandas
        release the GIL for the heavy parts)."""
        if parallel and len(self.shards) > 1:
            with concurrent.futures.ThreadPoolExecutor() as ex:
                out = list(ex.map(lambda s: fn(s, *args), self.shards))
        else:
            out = [fn(s, *args) for s in self.shards]
        return XShards(out)

    def collect(self) -> List[Any]:
        return list(self.shards)

    def num_partitions(self) -> int:
        return len(self.shards)

    def repartition(self, num_partitions: int) -> "XShards":
        """Re-split preserving order (`shard.py` repartition). DataFrame
        shards keep their schema (row-range split, not pytree split)."""
        import pandas as pd
        rows = self._concat_rows()
        if isinstance(rows, pd.DataFrame):
            parts = np.array_split(np.arange(len(rows)), num_partitions)
            return XShards([rows.iloc[idx].reset_index(drop=True)
                            for idx in parts])
        return XShards.partition(rows, num_partitions)

    def partition_by(self, cols: str, num_partitions: Optional[int] = None
                     ) -> "XShards":
        """Hash-partition DataFrame shards by a column
        (`SparkXShards.partition_by`)."""
        import pandas as pd
        df = pd.concat(self.shards, ignore_index=True)
        n = num_partitions or self.num_partitions()
        codes = pd.util.hash_array(df[cols].to_numpy()) % n
        return XShards([df[codes == i].reset_index(drop=True)
                        for i in range(n)])

    def zip(self, other: "XShards") -> "XShards":
        """Pair shards elementwise (`SparkXShards.zip`); shard row counts
        must line up."""
        if self.num_partitions() != other.num_partitions():
            raise ValueError("zip needs equal partition counts")
        return XShards(list(zip(self.shards, other.shards)))

    # -- materialization ---------------------------------------------------
    def _concat_rows(self):
        import jax
        import pandas as pd
        first = self.shards[0]
        if isinstance(first, pd.DataFrame):
            return pd.concat(self.shards, ignore_index=True)
        leaves_list = [jax.tree_util.tree_flatten(s)[0] for s in self.shards]
        treedef = jax.tree_util.tree_flatten(first)[1]
        merged = [np.concatenate([ls[i] for ls in leaves_list])
                  for i in range(len(leaves_list[0]))]
        return jax.tree_util.tree_unflatten(treedef, merged)

    def to_numpy(self):
        """Concatenate all shards into one structure."""
        return self._concat_rows()

    def len(self) -> int:
        import pandas as pd
        total = 0
        for s in self.shards:
            if isinstance(s, pd.DataFrame):
                total += len(s)
            else:
                import jax
                leaves = jax.tree_util.tree_leaves(s)
                total += len(leaves[0]) if leaves else 0
        return total

    __len__ = len

    # -- persistence (`XShards.save/load` pickle semantics) ---------------
    def save_pickle(self, path: str) -> "XShards":
        with open(path, "wb") as fh:
            pickle.dump(self.shards, fh)
        return self

    @staticmethod
    def load_pickle(path: str) -> "XShards":
        with open(path, "rb") as fh:
            return XShards(pickle.load(fh))

    def __repr__(self):
        return f"XShards({self.num_partitions()} partitions)"


# The reference's name for the concrete Spark-backed implementation; identical
# surface here (no Spark), kept for source compatibility.
SparkXShards = XShards
