"""ctypes bridge to the native C++ batch loader (`native/zoo_loader.cpp`).

The reference's data-cache native layer is JNI into memkind/PMEM
(`PersistentMemoryAllocator.java:37`, `pmem/FeatureSet.scala:151`); here the
native side is a threaded mmap gather: samples are packed into one
fixed-record binary file, C++ workers assemble shuffled batches off the GIL
into a bounded queue, Python drains ready batches and splits each record
back into the pytree leaves. Falls back cleanly when no compiler is present
(`available()` gates every use).

Build: compiled on demand with g++ -O3 into the package dir; rebuilt when
the source is newer (no pip, no cmake — the image bakes the toolchain).
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import tempfile
import threading
from typing import List, Optional, Tuple

import numpy as np

log = logging.getLogger("analytics_zoo_tpu.native")

_SRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native", "zoo_loader.cpp")
_LIB_PATH = os.path.join(os.path.dirname(_SRC), "_zoo_loader.so")
_lib = None
_lib_lock = threading.Lock()
_build_failed = False


def build_native_lib(src: str, lib_path: str) -> Optional[ctypes.CDLL]:
    """Shared native-build contract for every on-demand C++ helper:
    honors ZOO_DISABLE_NATIVE=1, rebuilds when the source is newer, and
    recovers once from a stale/truncated .so (a killed build). Returns a
    loaded CDLL or None (caller falls back to the python path)."""
    if os.environ.get("ZOO_DISABLE_NATIVE") == "1":
        return None

    def compile_() -> Optional[str]:
        if os.path.exists(lib_path) and \
                os.path.getmtime(lib_path) >= os.path.getmtime(src):
            return lib_path
        # compile to a private temp file and rename: concurrent processes
        # (multi-process fit on one host) must never dlopen a half-written
        # .so or unlink each other's output
        tmp = f"{lib_path}.tmp.{os.getpid()}"
        cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
               src, "-o", tmp]
        try:
            subprocess.run(cmd, check=True, capture_output=True,
                           timeout=120)
            os.replace(tmp, lib_path)       # atomic publication
            return lib_path
        except (OSError, subprocess.SubprocessError) as e:
            log.warning("native build of %s failed (%s); using python "
                        "path", os.path.basename(src), e)
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return None

    path = compile_()
    if path is None:
        return None
    try:
        return ctypes.CDLL(path)
    except OSError:
        # stale/truncated artifact (e.g. a killed build): rebuild once
        try:
            os.unlink(path)
            path = compile_()
            if path:
                return ctypes.CDLL(path)
        except OSError:
            pass
        log.warning("native .so %s unloadable; using python path",
                    os.path.basename(lib_path))
        return None


def _get_lib():
    global _lib, _build_failed
    with _lib_lock:
        if _lib is not None or _build_failed:
            return _lib
        lib = build_native_lib(_SRC, _LIB_PATH)
        if lib is None:
            _build_failed = True
            return None
        lib.zoo_loader_create.restype = ctypes.c_void_p
        lib.zoo_loader_create.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int, ctypes.c_int, ctypes.c_int]
        lib.zoo_loader_start_epoch.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int]
        lib.zoo_loader_next.restype = ctypes.c_int64
        lib.zoo_loader_next.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
        lib.zoo_loader_destroy.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def available() -> bool:
    return _get_lib() is not None


class NativeBatchLoader:
    """Packed-record file + native threaded batch assembly.

    from_arrays packs a pytree-flattened list of arrays (shared leading dim)
    row-wise into one binary file; iter_epoch yields per-batch leaf lists.
    """

    def __init__(self, path: str, n: int, specs: List[Tuple[Tuple[int, ...],
                                                            np.dtype]],
                 batch_size: int, n_threads: int = 2,
                 queue_capacity: int = 4, drop_remainder: bool = True,
                 _owns_file: bool = False):
        lib = _get_lib()
        if lib is None:
            raise RuntimeError("native loader unavailable")
        self._lib = lib
        self.path, self.n, self.specs = path, n, specs
        self.batch_size = batch_size
        self.drop_remainder = drop_remainder
        self._owns_file = _owns_file
        self._row_bytes = [int(np.prod(shape)) * np.dtype(dt).itemsize
                           for shape, dt in specs]
        self.record_bytes = sum(self._row_bytes)
        self._handle = lib.zoo_loader_create(
            path.encode(), n, self.record_bytes, batch_size,
            n_threads, queue_capacity, int(drop_remainder))
        if not self._handle:
            raise RuntimeError(f"zoo_loader_create failed for {path}")
        self._buf = np.empty(batch_size * self.record_bytes, np.uint8)
        self._lock = threading.Lock()
        self._epoch_token = 0

    @staticmethod
    def pack_file(leaves: List[np.ndarray], cache_dir: Optional[str] = None,
                  chunk_rows: int = 8192
                  ) -> Tuple[str, int, List[Tuple[Tuple[int, ...],
                                                  np.dtype]]]:
        """Stream leaves (ndarrays or memmaps) into a packed record file in
        chunks — peak RAM is chunk_rows * record_bytes, never the dataset
        (the DISK tier's whole point). Returns (path, n, specs)."""
        n = len(leaves[0])
        if any(len(a) != n for a in leaves):
            raise ValueError("leaves must share the leading dim")
        specs = [(a.shape[1:], np.dtype(a.dtype)) for a in leaves]
        fd, path = tempfile.mkstemp(suffix=".zoorec", dir=cache_dir)
        with os.fdopen(fd, "wb") as fh:
            for s in range(0, n, chunk_rows):
                e = min(s + chunk_rows, n)
                rows = [np.ascontiguousarray(a[s:e]) for a in leaves]
                packed = np.concatenate(
                    [r.reshape(e - s, -1).view(np.uint8)
                     .reshape(e - s, -1) for r in rows], axis=1)
                packed.tofile(fh)
        return path, n, specs

    @classmethod
    def from_arrays(cls, leaves: List[np.ndarray], batch_size: int,
                    cache_dir: Optional[str] = None,
                    **kw) -> "NativeBatchLoader":
        path, n, specs = cls.pack_file(leaves, cache_dir)
        return cls(path, n, specs, batch_size, _owns_file=True, **kw)

    def _split_record_batch(self, raw: np.ndarray, rows: int):
        """[rows, record_bytes] uint8 -> list of leaf batches."""
        out = []
        off = 0
        for (shape, dt), nb in zip(self.specs, self._row_bytes):
            # .copy() (never ascontiguousarray): the staging buffer is
            # reused next iteration, so yielded batches must own their data
            chunk = raw[:rows, off:off + nb].copy()
            out.append(chunk.view(dt).reshape((rows,) + tuple(shape)))
            off += nb
        return out

    def iter_epoch(self, seed: int = 0, shuffle: bool = True):
        """Yield lists of leaf batches. Starting a new epoch supersedes any
        half-read one (the abandoned generator just stops) — the lock is
        only held per batch, never across the epoch, so an abandoned
        generator can never deadlock a later one."""
        with self._lock:
            self._epoch_token += 1
            token = self._epoch_token
            self._lib.zoo_loader_start_epoch(self._handle, seed,
                                             int(shuffle))
        raw2d = self._buf.reshape(self.batch_size, self.record_bytes)
        while True:
            with self._lock:
                if token != self._epoch_token:
                    return                      # superseded by a new epoch
                if self._handle is None:
                    raise RuntimeError("loader closed during iteration")
                rows = self._lib.zoo_loader_next(
                    self._handle,
                    self._buf.ctypes.data_as(ctypes.c_void_p))
                if rows == 0:
                    return
                if rows < 0:
                    raise RuntimeError("native loader shut down")
                batch = self._split_record_batch(raw2d, int(rows))
            yield batch

    def close(self):
        if getattr(self, "_handle", None):
            self._lib.zoo_loader_destroy(self._handle)
            self._handle = None
        if self._owns_file and os.path.exists(self.path):
            os.unlink(self.path)

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass
