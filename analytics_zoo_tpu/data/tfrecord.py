"""TFRecord ingestion: wire-format reader/writer + tf.train.Example codec.

The reference feeds training from TFRecord corpora through its TFDataset
family (`pyzoo/zoo/tfpark/tf_dataset.py:593` `from_tf_data_dataset`, `:911`
`TFBytesDataset`; the inception example trains from ImageNet TFRecords).
This module is the TPU-native path from a record-file corpus to the
trainer, with no tensorflow dependency:

- the TFRecord framing (little-endian u64 length, masked crc32c of the
  length, payload, masked crc32c of the payload) is decoded directly;
- `tf.train.Example` protobuf payloads are decoded with the same minimal
  wire codec the ONNX importer uses (`analytics_zoo_tpu/onnx/wire.py`) —
  the Example schema is tiny and frozen;
- `TPUDataset.from_tfrecord` (in `data/dataset.py`) streams shards through
  a shuffle buffer into the static-shape batch contract.

CRC32C (Castagnoli) is table-driven pure Python. Integrity checks default
to on for the 12-byte frame header (catches truncation/misalignment
cheaply) and off for payloads — pass `verify_payload=True` to check those
too.
"""

from __future__ import annotations

import glob as _glob
import os
import struct
from typing import (Any, Dict, Iterable, Iterator, List, Optional,
                    Sequence, Tuple)

import numpy as np

from analytics_zoo_tpu.onnx import wire

from analytics_zoo_tpu.utils.crc import crc32c, masked_crc32c  # noqa: F401

# ---------------------------------------------------------------------------
# Native fast path (`native/tfrecord_scanner.cpp`): frame walk + CRC32C at
# memory bandwidth; built on demand like the zoo_loader, python fallback
# when no compiler is present.
# ---------------------------------------------------------------------------
import ctypes as _ctypes
import logging as _logging
import threading as _threading

_log = _logging.getLogger("analytics_zoo_tpu.tfrecord")
_NATIVE_SRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native",
    "tfrecord_scanner.cpp")
_NATIVE_LIB = os.path.join(os.path.dirname(_NATIVE_SRC),
                           "_tfrecord_scanner.so")
_native = None
_native_lock = _threading.Lock()
_native_failed = False


def _native_lib():
    """Build (once) and load the scanner via the shared native-build
    contract (ZOO_DISABLE_NATIVE, stale-.so recovery); None → python
    fallback."""
    global _native, _native_failed
    if _native is not None or _native_failed:
        return _native
    with _native_lock:
        if _native is not None or _native_failed:
            return _native
        from analytics_zoo_tpu.data.native_loader import build_native_lib
        lib = build_native_lib(_NATIVE_SRC, _NATIVE_LIB)
        if lib is None:
            _native_failed = True
            return None
        lib.tfr_scan.restype = _ctypes.c_long
        lib.tfr_scan.argtypes = [
            _ctypes.c_char_p, _ctypes.c_int,
            _ctypes.POINTER(_ctypes.c_int64),
            _ctypes.POINTER(_ctypes.c_int64), _ctypes.c_long]
        lib.tfr_count.restype = _ctypes.c_long
        lib.tfr_count.argtypes = [_ctypes.c_char_p]
        _native = lib
    return _native


_NATIVE_ERRORS = {
    -1: "cannot open/read",
    -2: "truncated record",
    -3: "corrupt record length CRC",
    -4: "record count grew during scan",
    -5: "corrupt record payload CRC",
}

# one pass covers files with up to 4M records (2 × 32 MB index arrays);
# only bigger corpora pay an extra exact-count pass
_SCAN_CAP = 1 << 22


def _raise_located(path: str, verify_payload: bool, code: int):
    """Turn a native scan error code into an actionable error NAMING
    THE OFFSET: re-walk the frames pythonically (error path only — the
    file is already known bad) so a torn tail or a flipped bit reports
    `file + byte offset` instead of a bare error code. If the python
    walk disagrees (file changed under us), fall back to the coded
    message."""
    try:
        for _ in _python_frame_walk(path, verify_payload,
                                    read_payloads=verify_payload):
            pass
    except ValueError:
        raise
    except Exception:  # noqa: BLE001 — diagnosis only; keep coded error
        pass
    raise ValueError(
        f"{path}: {_NATIVE_ERRORS.get(code, f'scan error {code}')}")


def _native_scan(path: str, verify_payload: bool):
    """Native frame walk → (offsets, lengths) numpy arrays, or None when
    the native path is unavailable."""
    lib = _native_lib()
    if lib is None:
        return None

    def scan(cap):
        offsets = np.empty(cap, np.int64)
        lengths = np.empty(cap, np.int64)
        n = lib.tfr_scan(
            path.encode(), int(verify_payload),
            offsets.ctypes.data_as(_ctypes.POINTER(_ctypes.c_int64)),
            lengths.ctypes.data_as(_ctypes.POINTER(_ctypes.c_int64)), cap)
        return n, offsets, lengths

    # bounded first pass; on overflow (huge corpus or a writer appending
    # between passes) retry once with the exact count
    cap = max(1, min(os.path.getsize(path) // 16, _SCAN_CAP))
    n, offsets, lengths = scan(cap)
    if n == -4:
        count = lib.tfr_count(path.encode())
        if count < 0:
            _raise_located(path, verify_payload, int(count))
        n, offsets, lengths = scan(max(1, int(count)))
    if n < 0:
        _raise_located(path, verify_payload, int(n))
    return offsets[:n], lengths[:n]


# ---------------------------------------------------------------------------
# Record framing
# ---------------------------------------------------------------------------
class TFRecordWriter:
    """Writes the TFRecord framing; records are arbitrary bytes."""

    def __init__(self, path: str):
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._fh = open(path, "wb")

    def write(self, record: bytes) -> None:
        header = struct.pack("<Q", len(record))
        self._fh.write(header)
        self._fh.write(struct.pack("<I", masked_crc32c(header)))
        self._fh.write(record)
        self._fh.write(struct.pack("<I", masked_crc32c(record)))

    def close(self) -> None:
        self._fh.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def write_tfrecord(path: str, records: Iterable[bytes]) -> int:
    with TFRecordWriter(path) as w:
        n = 0
        for r in records:
            w.write(r)
            n += 1
    return n


def _python_frame_walk(path: str, verify_payload: bool,
                       read_payloads: bool = True):
    """Pure-python frame walk yielding (record_offset, payload|None).
    Every integrity error names the file AND the byte offset of the
    torn/corrupt frame — a mid-stream failure must be actionable (which
    shard, where) rather than a bare 'truncated'. With
    `read_payloads=False` payloads are seeked over, not read (the
    count_records fast path)."""
    size = os.path.getsize(path)
    with open(path, "rb") as fh:
        pos = 0
        while pos < size:
            header = fh.read(8)
            if len(header) < 8:
                raise ValueError(
                    f"{path}: truncated record header at offset {pos} "
                    f"(file ends {size - pos} bytes into a frame)")
            (length,) = struct.unpack("<Q", header)
            len_crc_raw = fh.read(4)
            if len(len_crc_raw) < 4:
                raise ValueError(
                    f"{path}: truncated record header at offset {pos}")
            if struct.unpack("<I", len_crc_raw)[0] != masked_crc32c(header):
                raise ValueError(
                    f"{path}: corrupt record length CRC at offset {pos}")
            payload = None
            if read_payloads or verify_payload:
                payload = fh.read(length)
                got = len(payload)
            else:
                end = min(pos + 12 + length, size)
                fh.seek(end)
                got = end - pos - 12
            if got < length:
                raise ValueError(
                    f"{path}: truncated record payload at offset {pos} "
                    f"(payload needs {length} bytes, file has {got})")
            crc_raw = fh.read(4)
            if len(crc_raw) < 4:
                raise ValueError(
                    f"{path}: truncated record payload at offset {pos}")
            if verify_payload and struct.unpack("<I", crc_raw)[0] \
                    != masked_crc32c(payload):
                raise ValueError(
                    f"{path}: corrupt record payload CRC at offset {pos}")
            yield pos, payload, length
            pos += 12 + length + 4


def read_records(path: str, verify_payload: bool = False
                 ) -> Iterator[bytes]:
    """Yield raw record payloads from one TFRecord file. The 12-byte frame
    header CRC is always verified (cheap, catches corruption/misalignment
    immediately); payload CRC only under `verify_payload`. Uses the native
    C++ scanner when buildable (frame walk + CRC at memory bandwidth),
    python frame walk otherwise. Integrity errors name file + offset
    on both paths."""
    scanned = _native_scan(path, verify_payload)
    if scanned is not None:
        offsets, lengths = scanned
        yield from read_payloads_at(path, offsets, lengths)
        return
    for _pos, payload, _len in _python_frame_walk(path, verify_payload):
        yield payload


def scan_index(path: str, verify_payload: bool = False):
    """Header-only record index: (payload_offsets, payload_lengths)
    int64 arrays for every record in the file — what the sub-shard
    pipeline seeks by (`data/dataset.py` splits big files into bounded
    record ranges so a worker never holds more than a range, not the
    file). Native scan when buildable; python frame walk otherwise.
    Integrity errors name file + offset like every other entry point.
    With `verify_payload` the payload CRCs are checked during the scan
    (the later seek-reads trust the scanned index)."""
    scanned = _native_scan(path, verify_payload)
    if scanned is not None:
        return scanned
    offs: List[int] = []
    lens: List[int] = []
    for pos, _payload, length in _python_frame_walk(
            path, verify_payload, read_payloads=verify_payload):
        offs.append(pos + 12)
        lens.append(length)
    return np.asarray(offs, np.int64), np.asarray(lens, np.int64)


def read_payloads_at(path: str, offsets, lengths) -> Iterator[bytes]:
    """Yield payloads by (offset, length) pairs from a `scan_index` —
    the seek-read back half shared by `read_records`' native path and
    the sub-shard range reader."""
    with open(path, "rb") as fh:
        for off, ln in zip(offsets, lengths):
            fh.seek(int(off))
            yield fh.read(int(ln))


def count_records(path: str) -> int:
    """Count records by walking frame headers only (no payload decode).
    Header CRCs are verified and truncation detected, so a corrupt or
    non-TFRecord file raises here the same way `read_records` would."""
    lib = _native_lib()
    if lib is not None:
        n = lib.tfr_count(path.encode())
        if n < 0:
            _raise_located(path, False, int(n))
        return int(n)
    return sum(1 for _ in _python_frame_walk(path, False,
                                             read_payloads=False))


# ---------------------------------------------------------------------------
# tf.train.Example codec (schema frozen in tensorflow/core/example/*.proto)
# ---------------------------------------------------------------------------
_BYTES_LIST = {1: ("value", "bytes")}
_FLOAT_LIST = {1: ("value", "float")}
_INT64_LIST = {1: ("value", "varint")}
_FEATURE = {
    1: ("bytes_list", ("msg", _BYTES_LIST)),
    2: ("float_list", ("msg", _FLOAT_LIST)),
    3: ("int64_list", ("msg", _INT64_LIST)),
}
_MAP_ENTRY = {1: ("key", "string"), 2: ("value", ("msg", _FEATURE))}
_FEATURES = {1: ("feature", ("msg", _MAP_ENTRY))}
_EXAMPLE = {1: ("features", ("msg", _FEATURES))}

_U64 = 1 << 64
_I64_MAX = (1 << 63) - 1


def _raw_features(payload: bytes) -> Dict[str, Tuple[str, list]]:
    """Decode the Example wire message to {name: (kind, raw values)}
    without building per-feature numpy arrays — the shared front half
    of `decode_example` (per-sample arrays) and `decode_example_batch`
    (ONE array per feature column across the whole frame batch)."""
    msg = wire.decode(payload, _EXAMPLE)
    out: Dict[str, Tuple[str, list]] = {}
    for features in msg.get("features", []):
        for entry in features.get("feature", []):
            key = entry["key"][0]
            feat = entry["value"][0]
            if "bytes_list" in feat:
                out[key] = ("bytes",
                            list(feat["bytes_list"][0].get("value", [])))
            elif feat.get("float_list"):
                out[key] = ("float",
                            feat["float_list"][0].get("value", []))
            elif feat.get("int64_list"):
                out[key] = ("int", feat["int64_list"][0].get("value", []))
            else:  # empty feature of unknown kind
                out[key] = ("empty", [])
    return out


def _feature_array(kind: str, vals: list):
    """One feature's decoded value, matching the decode_example
    contract exactly (int64/float32 ndarrays, list of bytes)."""
    if kind == "bytes":
        return list(vals)
    if kind == "float":
        return np.asarray(vals, np.float32)
    if kind == "int":
        # stored unsigned; uint64→int64 bit view is exactly v - 2^64
        # for values past I64_MAX
        return np.asarray(vals, np.uint64).view(np.int64)
    return np.asarray([], np.float32)


def decode_example(payload: bytes) -> Dict[str, Any]:
    """tf.train.Example bytes → {name: np.ndarray | list[bytes]}.
    int64 features come back as int64 ndarrays, float features as float32
    ndarrays, bytes features as a list of bytes objects."""
    return {key: _feature_array(kind, vals)
            for key, (kind, vals) in _raw_features(payload).items()}


def decode_example_batch(payloads: Sequence[bytes]) -> List[Dict[str, Any]]:
    """Vectorized frame-batch decode (ISSUE 15): decode a BATCH of
    `tf.train.Example` payloads into per-sample dicts whose arrays are
    rows of ONE `(B, n)` array per feature column — one numpy
    construction per (feature, batch) instead of one per (feature,
    record), and the int64 sign fixup becomes a single uint64→int64
    bit view over the whole column instead of a per-value python
    branch. Columns that are ragged across the batch (or missing from
    some records) fall back to the per-sample build. Values are
    bitwise-identical to `decode_example` per record — parity-tested."""
    raws = [_raw_features(p) for p in payloads]
    n = len(raws)
    if n == 0:
        return []
    out: List[Dict[str, Any]] = [{} for _ in range(n)]
    for key in list(raws[0]):
        col = [r.get(key) for r in raws]
        kind, width = col[0][0], len(col[0][1])
        uniform = kind in ("float", "int") and width > 0 and all(
            c is not None and c[0] == kind and len(c[1]) == width
            for c in col)
        if uniform:
            vals = [c[1] for c in col]
            if kind == "float":
                stacked = np.asarray(vals, np.float32)
            else:
                stacked = np.asarray(vals, np.uint64).view(np.int64)
            for i in range(n):
                out[i][key] = stacked[i]
            for r in raws:
                r.pop(key, None)
    for i, r in enumerate(raws):     # non-uniform / leftover features
        for key, (kind, vals) in r.items():
            out[i][key] = _feature_array(kind, vals)
    return out


def encode_example(features: Dict[str, Any]) -> bytes:
    """{name: value} → tf.train.Example bytes. Value kinds: bytes/str (or
    lists of them) → bytes_list; float arrays → float_list; int arrays →
    int64_list."""
    entries = []
    for key, value in features.items():
        if isinstance(value, (bytes, str)):
            feat = {"bytes_list": {"value": [
                value.encode() if isinstance(value, str) else value]}}
        elif isinstance(value, (list, tuple)) and value \
                and isinstance(value[0], (bytes, str)):
            feat = {"bytes_list": {"value": [
                v.encode() if isinstance(v, str) else v for v in value]}}
        else:
            arr = np.asarray(value)
            flat = arr.ravel()
            if np.issubdtype(arr.dtype, np.integer):
                feat = {"int64_list": {"value": [
                    int(v) + _U64 if v < 0 else int(v) for v in flat]}}
            elif np.issubdtype(arr.dtype, np.floating):
                feat = {"float_list": {"value": [float(v) for v in flat]}}
            else:
                raise TypeError(
                    f"Feature {key!r}: unsupported dtype {arr.dtype}")
        entries.append({"key": [key], "value": [feat]})
    return wire.encode({"features": [{"feature": entries}]}, _EXAMPLE)


# ---------------------------------------------------------------------------
# Corpus helpers
# ---------------------------------------------------------------------------
def expand_files(paths) -> List[str]:
    """Glob pattern / directory / explicit list → sorted file list. An
    explicitly-listed path that doesn't exist raises (a typo'd shard must
    not silently train on a partial corpus)."""
    if isinstance(paths, str):
        if os.path.isdir(paths):
            paths = sorted(
                os.path.join(paths, f) for f in os.listdir(paths)
                if not f.startswith("."))
        else:
            paths = sorted(_glob.glob(paths)) or [paths]
    missing = [p for p in paths if not os.path.isfile(p)]
    if missing:
        raise FileNotFoundError(
            f"TFRecord shard(s) not found: {missing!r}")
    if not paths:
        raise FileNotFoundError("Empty TFRecord file list")
    return list(paths)


def iter_examples(paths, parse_fn=None, verify_payload: bool = False
                  ) -> Iterator[Any]:
    """Stream decoded Examples (or `parse_fn(example_dict)` results) across
    a shard list in order."""
    for path in expand_files(paths):
        for payload in read_records(path, verify_payload=verify_payload):
            ex = decode_example(payload)
            yield parse_fn(ex) if parse_fn is not None else ex
