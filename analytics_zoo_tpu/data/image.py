"""ImageSet + image preprocessing pipeline.

The reference's distributed image pipeline (`zoo/.../feature/image/
ImageSet.scala:368` + OpenCV-backed `ImageProcessing` transforms inherited
from BigDL: Resize/Crop/Normalize/Brightness/Flip, python mirrors
`pyzoo/zoo/feature/image/imagePreprocessing.py`). Same composable-transform
surface here over numpy/cv2 on the host; the output feeds the mesh as NHWC
float batches (TPU-native layout). Host-side augmentation parallelizes over
XShards; device-side normalization could fuse into the jit program but is
kept host-side for reference parity.
"""

from __future__ import annotations

import glob
import os
from typing import Any, Callable, List, Optional, Sequence, Tuple

import numpy as np

try:
    import cv2
    _HAS_CV2 = True
except ImportError:  # pragma: no cover - cv2 is present in the base image
    _HAS_CV2 = False


def _require_cv2():
    if not _HAS_CV2:
        raise ImportError(
            "opencv-python (cv2) is required for image decoding/resizing; "
            "it is unavailable in this environment")


def load_image(value) -> np.ndarray:
    """Image path or raw encoded bytes -> RGB HWC uint8 ndarray (the serving
    client's image ingestion; reference ships b64 JPEG, `client.py:114`)."""
    _require_cv2()
    if isinstance(value, (bytes, bytearray)):
        arr = cv2.imdecode(np.frombuffer(bytes(value), np.uint8),
                           cv2.IMREAD_COLOR)
    else:
        arr = cv2.imread(str(value))
    if arr is None:
        raise ValueError("Could not decode image input")
    return cv2.cvtColor(arr, cv2.COLOR_BGR2RGB)


class ImageProcessing:
    """Composable transform; `>>` or `chain` composes (the reference's
    `->` pipeline operator)."""

    def apply(self, img: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def __call__(self, img):
        return self.apply(img)

    def __rshift__(self, other: "ImageProcessing") -> "ChainedPreprocessing":
        return ChainedPreprocessing([self, other])


class ChainedPreprocessing(ImageProcessing):
    def __init__(self, transforms: Sequence[ImageProcessing]):
        self.transforms = list(transforms)

    def apply(self, img):
        for t in self.transforms:
            img = t.apply(img)
        return img

    def __rshift__(self, other):
        return ChainedPreprocessing(self.transforms + [other])


class ImageResize(ImageProcessing):
    """`ImageResize` (bilinear, W×H)."""

    def __init__(self, resize_h: int, resize_w: int):
        self.h, self.w = resize_h, resize_w

    def apply(self, img):
        _require_cv2()
        return cv2.resize(img, (self.w, self.h),
                          interpolation=cv2.INTER_LINEAR)


class ImageCenterCrop(ImageProcessing):
    def __init__(self, crop_h: int, crop_w: int):
        self.h, self.w = crop_h, crop_w

    def apply(self, img):
        H, W = img.shape[:2]
        if H < self.h or W < self.w:
            raise ValueError(f"Image {H}x{W} smaller than crop "
                             f"{self.h}x{self.w}")
        y0 = (H - self.h) // 2
        x0 = (W - self.w) // 2
        return img[y0:y0 + self.h, x0:x0 + self.w]


class ImageRandomCrop(ImageProcessing):
    def __init__(self, crop_h: int, crop_w: int, seed: Optional[int] = None):
        self.h, self.w = crop_h, crop_w
        self.rng = np.random.RandomState(seed)

    def apply(self, img):
        H, W = img.shape[:2]
        if H < self.h or W < self.w:
            raise ValueError(f"Image {H}x{W} smaller than crop "
                             f"{self.h}x{self.w}")
        y0 = self.rng.randint(0, H - self.h + 1)
        x0 = self.rng.randint(0, W - self.w + 1)
        return img[y0:y0 + self.h, x0:x0 + self.w]


class ImageHFlip(ImageProcessing):
    """Horizontal flip with probability p (`ImageHFlip`)."""

    def __init__(self, p: float = 0.5, seed: Optional[int] = None):
        self.p = p
        self.rng = np.random.RandomState(seed)

    def apply(self, img):
        if self.rng.rand() < self.p:
            return img[:, ::-1].copy()
        return img


class ImageBrightness(ImageProcessing):
    """Additive brightness jitter in [delta_low, delta_high]
    (`ImageBrightness`)."""

    def __init__(self, delta_low: float = -32.0, delta_high: float = 32.0,
                 seed: Optional[int] = None):
        self.low, self.high = delta_low, delta_high
        self.rng = np.random.RandomState(seed)

    def apply(self, img):
        return img.astype(np.float32) + self.rng.uniform(self.low, self.high)


class ImageChannelNormalize(ImageProcessing):
    """(x - mean) / std per channel (`ImageChannelNormalize`)."""

    def __init__(self, mean_r: float, mean_g: float, mean_b: float,
                 std_r: float = 1.0, std_g: float = 1.0, std_b: float = 1.0):
        self.mean = np.array([mean_r, mean_g, mean_b], np.float32)
        self.std = np.array([std_r, std_g, std_b], np.float32)

    def apply(self, img):
        return (img.astype(np.float32) - self.mean) / self.std


def _as_uint8(img: np.ndarray) -> np.ndarray:
    if img.dtype == np.uint8:
        return img
    return np.clip(img, 0, 255).astype(np.uint8)


class ImageHue(ImageProcessing):
    """Random hue rotation: H += delta ∈ [delta_low, delta_high] in HSV
    space, wrapping over OpenCV's 0-180 hue range (`ImageHue.scala` /
    BigDL `augmentation.Hue`)."""

    def __init__(self, delta_low: float = -18.0, delta_high: float = 18.0,
                 seed: Optional[int] = None):
        self.low, self.high = delta_low, delta_high
        self.rng = np.random.RandomState(seed)

    def apply(self, img):
        _require_cv2()
        delta = self.rng.uniform(self.low, self.high)
        hsv = cv2.cvtColor(_as_uint8(img), cv2.COLOR_RGB2HSV).astype(
            np.int32)
        hsv[..., 0] = (hsv[..., 0] + int(round(delta))) % 180
        return cv2.cvtColor(hsv.astype(np.uint8), cv2.COLOR_HSV2RGB)


class ImageSaturation(ImageProcessing):
    """Random saturation scale: S *= f ∈ [delta_low, delta_high]
    (`ImageSaturation.scala`). A grayscale image is a fixed point."""

    def __init__(self, delta_low: float = 0.5, delta_high: float = 1.5,
                 seed: Optional[int] = None):
        self.low, self.high = delta_low, delta_high
        self.rng = np.random.RandomState(seed)

    def apply(self, img):
        _require_cv2()
        f = self.rng.uniform(self.low, self.high)
        hsv = cv2.cvtColor(_as_uint8(img), cv2.COLOR_RGB2HSV).astype(
            np.float32)
        hsv[..., 1] = np.clip(hsv[..., 1] * f, 0, 255)
        return cv2.cvtColor(hsv.astype(np.uint8), cv2.COLOR_HSV2RGB)


class ImageContrast(ImageProcessing):
    """Random contrast scale: x *= f ∈ [delta_low, delta_high] (BigDL
    `augmentation.Contrast`, the ColorJitter contrast leg)."""

    def __init__(self, delta_low: float = 0.5, delta_high: float = 1.5,
                 seed: Optional[int] = None):
        self.low, self.high = delta_low, delta_high
        self.rng = np.random.RandomState(seed)

    def apply(self, img):
        f = self.rng.uniform(self.low, self.high)
        return np.clip(img.astype(np.float32) * f, 0, 255).astype(img.dtype)


class ImageChannelOrder(ImageProcessing):
    """Random channel permutation (`ImageChannelOrder.scala`)."""

    def __init__(self, seed: Optional[int] = None):
        self.rng = np.random.RandomState(seed)

    def apply(self, img):
        return img[..., self.rng.permutation(img.shape[-1])]


class ImageColorJitter(ImageProcessing):
    """The SSD photometric distortion stack (`ImageColorJitter.scala`):
    probabilistic brightness, then contrast either before or after the
    saturation+hue pair (coin flip — the Caffe two-order rule), then
    probabilistic channel shuffle; `shuffle=True` instead applies all
    four ops in a random order."""

    def __init__(self, brightness_prob: float = 0.5,
                 brightness_delta: float = 32.0,
                 contrast_prob: float = 0.5, contrast_lower: float = 0.5,
                 contrast_upper: float = 1.5, hue_prob: float = 0.5,
                 hue_delta: float = 18.0, saturation_prob: float = 0.5,
                 saturation_lower: float = 0.5,
                 saturation_upper: float = 1.5,
                 random_order_prob: float = 0.0, shuffle: bool = False,
                 seed: Optional[int] = None):
        self.rng = np.random.RandomState(seed)

        def sub():
            return int(self.rng.randint(0, 2 ** 31 - 1))

        self.brightness = (brightness_prob, ImageBrightness(
            -brightness_delta, brightness_delta, seed=sub()))
        self.contrast = (contrast_prob, ImageContrast(
            contrast_lower, contrast_upper, seed=sub()))
        self.saturation = (saturation_prob, ImageSaturation(
            saturation_lower, saturation_upper, seed=sub()))
        self.hue = (hue_prob, ImageHue(-hue_delta, hue_delta, seed=sub()))
        self.channel_order = (random_order_prob,
                              ImageChannelOrder(seed=sub()))
        self.shuffle = shuffle

    def _maybe(self, img, prob_op):
        p, op = prob_op
        if self.rng.rand() < p:
            img = _as_uint8(op.apply(img))
        return img

    def apply(self, img):
        img = _as_uint8(img)
        if self.shuffle:
            ops = [self.brightness, self.contrast, self.saturation,
                   self.hue]
            for i in self.rng.permutation(len(ops)):
                img = self._maybe(img, ops[i])
        else:
            img = self._maybe(img, self.brightness)
            if self.rng.rand() < 0.5:
                img = self._maybe(img, self.contrast)
                img = self._maybe(img, self.saturation)
                img = self._maybe(img, self.hue)
            else:
                img = self._maybe(img, self.saturation)
                img = self._maybe(img, self.hue)
                img = self._maybe(img, self.contrast)
        return self._maybe(img, self.channel_order)


class ImageExpand(ImageProcessing):
    """Paste into a mean-filled canvas of random ratio ∈
    [min_expand_ratio, max_expand_ratio] at a random offset
    (`ImageExpand.scala`; the bbox-tracking variant is
    `data/roi.py RoiExpand`)."""

    def __init__(self, means_r: float = 123.0, means_g: float = 117.0,
                 means_b: float = 104.0, min_expand_ratio: float = 1.0,
                 max_expand_ratio: float = 4.0,
                 seed: Optional[int] = None):
        if min_expand_ratio < 1.0:
            raise ValueError("min_expand_ratio must be >= 1 (expand only "
                             "grows the canvas; use a crop to shrink)")
        self.means = np.array([means_r, means_g, means_b], np.float32)
        self.min_ratio, self.max_ratio = min_expand_ratio, max_expand_ratio
        self.rng = np.random.RandomState(seed)

    def apply(self, img):
        H, W = img.shape[:2]
        r = self.rng.uniform(self.min_ratio, self.max_ratio)
        nH, nW = int(round(H * r)), int(round(W * r))
        y0 = int(self.rng.uniform(0, nH - H + 1))
        x0 = int(self.rng.uniform(0, nW - W + 1))
        canvas = np.empty((nH, nW, img.shape[2]), img.dtype)
        canvas[...] = self.means.astype(img.dtype)
        canvas[y0:y0 + H, x0:x0 + W] = img
        return canvas


class ImageFiller(ImageProcessing):
    """Fill a normalized-coordinate sub-rectangle with a constant
    (occlusion augmentation, `ImageFiller.scala`)."""

    def __init__(self, start_x: float, start_y: float, end_x: float,
                 end_y: float, value: int = 255):
        if not (0 <= start_x <= end_x <= 1 and 0 <= start_y <= end_y <= 1):
            raise ValueError("filler rect must satisfy "
                             "0 <= start <= end <= 1")
        self.rect = (start_x, start_y, end_x, end_y)
        self.value = value

    def apply(self, img):
        H, W = img.shape[:2]
        x1, y1, x2, y2 = self.rect
        out = img.copy()
        out[int(y1 * H):int(y2 * H), int(x1 * W):int(x2 * W)] = self.value
        return out


class ImageFixedCrop(ImageProcessing):
    """Crop a fixed region given in normalized or pixel coordinates;
    `is_clip` clips the region to the image bounds first
    (`ImageFixedCrop.scala`)."""

    def __init__(self, x1: float, y1: float, x2: float, y2: float,
                 normalized: bool = True, is_clip: bool = True):
        self.box = (x1, y1, x2, y2)
        self.normalized = normalized
        self.is_clip = is_clip

    def apply(self, img):
        H, W = img.shape[:2]
        x1, y1, x2, y2 = self.box
        if self.normalized:
            x1, y1, x2, y2 = x1 * W, y1 * H, x2 * W, y2 * H
        if self.is_clip:
            x1, x2 = np.clip([x1, x2], 0.0, float(W))
            y1, y2 = np.clip([y1, y2], 0.0, float(H))
            x1, y1 = min(x1, W - 1.0), min(y1, H - 1.0)
        xi1, yi1 = int(round(x1)), int(round(y1))
        xi2, yi2 = max(xi1 + 1, int(round(x2))), max(yi1 + 1,
                                                     int(round(y2)))
        if not (0 <= xi1 < W and 0 <= yi1 < H and xi2 <= W and yi2 <= H):
            raise ValueError(
                f"crop {self.box} out of bounds for {H}x{W} image" +
                ("" if self.is_clip else " (pass is_clip=True to clip)"))
        return img[yi1:yi2, xi1:xi2].copy()


class ImageMirror(ImageProcessing):
    """Flip around BOTH axes (`ImageMirror.scala` = `Core.flip(mat, -1)`);
    for the horizontal-only flip use `ImageHFlip`."""

    def apply(self, img):
        return img[::-1, ::-1].copy()


class ImageRandomResize(ImageProcessing):
    """Resize to SxS with S drawn uniformly from [min_size, max_size)
    (`ImageRandomResize.scala`)."""

    def __init__(self, min_size: int, max_size: int,
                 seed: Optional[int] = None):
        self.min_size, self.max_size = min_size, max_size
        self.rng = np.random.RandomState(seed)

    def apply(self, img):
        _require_cv2()
        s = int(self.rng.randint(self.min_size, max(self.min_size + 1,
                                                    self.max_size)))
        return cv2.resize(img, (s, s), interpolation=cv2.INTER_LINEAR)


class ImageAspectScale(ImageProcessing):
    """Scale the SHORT edge to min_size keeping aspect ratio, cap the long
    edge at max_size, round dims down to a multiple of scale_multiple_of
    (`ImageAspectScale` in the pyzoo surface / Faster-RCNN input prep)."""

    def __init__(self, min_size: int, scale_multiple_of: int = 1,
                 max_size: int = 1000):
        self.min_size = min_size
        self.multiple = scale_multiple_of
        self.max_size = max_size

    def _target(self, H: int, W: int) -> Tuple[int, int]:
        short, long = min(H, W), max(H, W)
        scale = self.min_size / short
        if long * scale > self.max_size:
            scale = self.max_size / long
        nH, nW = int(round(H * scale)), int(round(W * scale))
        if self.multiple > 1:
            nH = max(self.multiple, nH // self.multiple * self.multiple)
            nW = max(self.multiple, nW // self.multiple * self.multiple)
        return nH, nW

    def apply(self, img):
        _require_cv2()
        nH, nW = self._target(*img.shape[:2])
        return cv2.resize(img, (nW, nH), interpolation=cv2.INTER_LINEAR)


class ImageRandomAspectScale(ImageAspectScale):
    """Aspect-preserving scale with the short-edge target drawn from
    `scales` (`ImageRandomAspectScale`)."""

    def __init__(self, scales: Sequence[int], scale_multiple_of: int = 1,
                 max_size: int = 1000, seed: Optional[int] = None):
        super().__init__(int(scales[0]), scale_multiple_of, max_size)
        self.scales = [int(s) for s in scales]
        self.rng = np.random.RandomState(seed)

    def apply(self, img):
        # local draw, no shared-state mutation: transform objects are
        # called concurrently from the threaded pipeline
        _require_cv2()
        pick = self.scales[self.rng.randint(len(self.scales))]
        nH, nW = ImageAspectScale(
            pick, self.multiple, self.max_size)._target(*img.shape[:2])
        return cv2.resize(img, (nW, nH), interpolation=cv2.INTER_LINEAR)


class ImageChannelScaledNormalizer(ImageProcessing):
    """(x - mean_c) * scale (`ImageChannelScaledNormalizer.scala`)."""

    def __init__(self, mean_r: float, mean_g: float, mean_b: float,
                 scale: float):
        self.mean = np.array([mean_r, mean_g, mean_b], np.float32)
        self.scale = scale

    def apply(self, img):
        return (img.astype(np.float32) - self.mean) * self.scale


class ImagePixelNormalize(ImageProcessing):
    """Per-pixel mean subtraction: data - means, means in HWC order
    (`ImagePixelNormalizer.scala`)."""

    def __init__(self, means: np.ndarray):
        self.means = np.asarray(means, np.float32)

    def apply(self, img):
        if self.means.shape != img.shape:
            raise ValueError(
                f"pixel means shape {self.means.shape} != image shape "
                f"{img.shape}")
        return img.astype(np.float32) - self.means


# opencv NormTypes used by the reference's PerImageNormalize
NORM_INF, NORM_L1, NORM_L2, NORM_MINMAX = 1, 2, 4, 32


class PerImageNormalize(ImageProcessing):
    """Per-image cv::normalize semantics (`PerImageNormalize` in the pyzoo
    surface): MINMAX maps the value range onto [min, max]; the norm types
    scale so that the chosen norm equals `min`."""

    def __init__(self, min: float, max: float = 0.0,
                 norm_type: int = NORM_MINMAX):
        self.min, self.max = float(min), float(max)
        self.norm_type = norm_type

    def apply(self, img):
        x = img.astype(np.float32)
        if self.norm_type == NORM_MINMAX:
            lo, hi = float(x.min()), float(x.max())
            span = hi - lo if hi > lo else 1.0
            a, b = min(self.min, self.max), max(self.min, self.max)
            return (x - lo) / span * (b - a) + a
        norm = {NORM_INF: np.abs(x).max(),
                NORM_L1: np.abs(x).sum(),
                NORM_L2: np.sqrt((x * x).sum())}.get(self.norm_type)
        if norm is None:
            raise ValueError(f"Unsupported norm_type {self.norm_type}")
        return x * (self.min / max(float(norm), 1e-12))


class ImageRandomPreprocessing(ImageProcessing):
    """Apply the wrapped transform with probability p
    (`ImageRandomPreprocessing.scala`)."""

    def __init__(self, transform: ImageProcessing, p: float = 0.5,
                 seed: Optional[int] = None):
        self.transform = transform
        self.p = p
        self.rng = np.random.RandomState(seed)

    def apply(self, img):
        if self.rng.rand() < self.p:
            return self.transform.apply(img)
        return img


class ImageRandomCropper(ImageProcessing):
    """Fixed-size crop by random or center placement plus optional random
    horizontal mirror (`ImageRandomCropper.scala`, BigDL RandomCropper)."""

    def __init__(self, crop_width: int, crop_height: int,
                 mirror: bool = False, cropper_method: str = "random",
                 seed: Optional[int] = None):
        if cropper_method not in ("random", "center"):
            raise ValueError("cropper_method must be 'random' or 'center'")
        self.w, self.h = crop_width, crop_height
        self.mirror = mirror
        self.method = cropper_method
        self.rng = np.random.RandomState(seed)

    def apply(self, img):
        H, W = img.shape[:2]
        if H < self.h or W < self.w:
            raise ValueError(f"Image {H}x{W} smaller than crop "
                             f"{self.h}x{self.w}")
        if self.method == "center":
            y0, x0 = (H - self.h) // 2, (W - self.w) // 2
        else:
            y0 = self.rng.randint(0, H - self.h + 1)
            x0 = self.rng.randint(0, W - self.w + 1)
        out = img[y0:y0 + self.h, x0:x0 + self.w]
        if self.mirror and self.rng.rand() < 0.5:
            out = out[:, ::-1]
        return out.copy()


class ImageMatToTensor(ImageProcessing):
    """To float32; NHWC stays native (TPU conv layout) unless
    format='NCHW' requested (`ImageMatToTensor` toChw)."""

    def __init__(self, format: str = "NHWC"):
        self.format = format

    def apply(self, img):
        img = img.astype(np.float32)
        if self.format == "NCHW":
            return np.transpose(img, (2, 0, 1))
        return img


def parallel_map_ordered(fn, items: Sequence[Any], num_workers: int,
                         window: Optional[int] = None):
    """Order-preserving threaded map with a bounded in-flight window —
    decode/augment overlap without holding the whole corpus in futures.
    cv2 releases the GIL in decode/resize, so threads give real
    parallelism on the hot ops."""
    if num_workers <= 1:
        for it in items:
            yield fn(it)
        return
    import collections
    from concurrent.futures import ThreadPoolExecutor
    window = window or num_workers * 4
    with ThreadPoolExecutor(max_workers=num_workers) as pool:
        pending: "collections.deque" = collections.deque()
        it = iter(items)
        try:
            for _ in range(window):
                pending.append(pool.submit(fn, next(it)))
        except StopIteration:
            it = None
        while pending:
            done = pending.popleft()
            if it is not None:
                try:
                    pending.append(pool.submit(fn, next(it)))
                except StopIteration:
                    it = None
            yield done.result()


class ImageSet:
    """Collection of images + optional labels (`ImageSet.scala:368`
    read/transform surface), sharded like XShards."""

    def __init__(self, images: List[np.ndarray],
                 labels: Optional[np.ndarray] = None,
                 paths: Optional[List[str]] = None):
        self.images = images
        self.labels = labels
        self.paths = paths

    @staticmethod
    def _list_files(path: str) -> List[str]:
        if os.path.isdir(path):
            files = sorted(glob.glob(os.path.join(path, "**", "*.*"),
                                     recursive=True))
            files = [f for f in files if f.rsplit(".", 1)[-1].lower() in
                     ("jpg", "jpeg", "png", "bmp")]
        else:
            files = [path]
        if not files:
            raise FileNotFoundError(f"No images under {path}")
        return files

    @staticmethod
    def _folder_labels(files: List[str],
                       one_based_label: bool) -> np.ndarray:
        classes = sorted({os.path.basename(os.path.dirname(f))
                          for f in files})
        base = 1 if one_based_label else 0
        cls_idx = {c: i + base for i, c in enumerate(classes)}
        return np.array([cls_idx[os.path.basename(os.path.dirname(f))]
                         for f in files], np.int32)

    @staticmethod
    def read(path: str, with_label: bool = False,
             one_based_label: bool = True,
             num_workers: int = 1) -> "ImageSet":
        """Read image file/dir (optionally `dir/<class>/img.jpg` layout for
        labels, like `ImageSet.read` + label resolution); `num_workers > 1`
        decodes in a thread pool."""
        files = ImageSet._list_files(path)
        _require_cv2()
        images = list(parallel_map_ordered(load_image, files, num_workers))
        labels = (ImageSet._folder_labels(files, one_based_label)
                  if with_label else None)
        return ImageSet(images, labels, files)

    def transform(self, transformer: ImageProcessing,
                  num_workers: int = 1) -> "ImageSet":
        return ImageSet(list(parallel_map_ordered(
            transformer, self.images, num_workers)),
            self.labels, self.paths)

    def to_dataset(self, batch_size: int = -1, batch_per_thread: int = -1):
        from analytics_zoo_tpu.data.dataset import TPUDataset
        x = np.stack(self.images)
        return TPUDataset(x, self.labels, batch_size, batch_per_thread)

    def __len__(self):
        return len(self.images)


def image_folder_dataset(path: str, transform=None,
                         with_label: bool = True,
                         one_based_label: bool = False,
                         batch_size: int = -1, batch_per_thread: int = -1,
                         shuffle: bool = True, num_workers: int = 8,
                         prefetch_batches: int = 2):
    """Lazy `dir/<class>/img.jpg` dataset: JPEG decode + augmentation run
    in a thread pool overlapped with the training step, so image training
    is not single-thread-Python bound (the role of the reference's
    per-executor OpenCV pipeline feeding `FeatureSet`; here the
    parallelism is host threads instead of Spark partitions).

    `transform` must produce a fixed output shape (the batch is stacked).
    With num_workers > 1 the per-op RNG draws land in nondeterministic
    order across samples — seed order is only reproducible at
    num_workers=1."""
    files = ImageSet._list_files(path)
    labels = (ImageSet._folder_labels(files, one_based_label)
              if with_label else None)
    return _ImageFolderDataset(files, labels, transform, batch_size,
                               batch_per_thread, shuffle, num_workers,
                               prefetch_batches)


def _default_float(img):
    return np.asarray(img, np.float32)


_folder_dataset_cls = None


def _ImageFolderDataset(*args, **kwargs):
    """Lazy TPUDataset over image files (decode+augment in threads). The
    class is built once on first use against a late TPUDataset import
    (avoids the dataset<->image import cycle)."""
    global _folder_dataset_cls
    if _folder_dataset_cls is None:
        from analytics_zoo_tpu.data.dataset import TPUDataset

        class _Impl(TPUDataset):
            def __init__(self, files, labels, transform, batch_size,
                         batch_per_thread, shuffle, num_workers,
                         prefetch_batches):
                super().__init__(x=None, y=None, batch_size=batch_size,
                                 batch_per_thread=batch_per_thread,
                                 shuffle=shuffle)
                self._files = files
                self._labels = labels
                self._transform = transform or _default_float
                self._workers = num_workers
                self._prefetch = max(1, prefetch_batches)

            def _load_one(self, i: int):
                img = self._transform(load_image(self._files[i]))
                y = None if self._labels is None else self._labels[i]
                return np.asarray(img, np.float32), y

            def n_samples(self) -> int:
                return len(self._files)

            def first_sample(self):
                return self._load_one(0)

            def materialize(self):
                pairs = list(parallel_map_ordered(
                    self._load_one, range(len(self._files)),
                    self._workers))
                x = np.stack([p[0] for p in pairs])
                y = None if self._labels is None \
                    else np.asarray([p[1] for p in pairs])
                return x, y

            def iter_train(self, data_parallel: int, seed: int = 0):
                batch = self.global_batch(data_parallel)
                order = np.arange(len(self._files))
                if self.shuffle:
                    np.random.RandomState(seed).shuffle(order)
                # bounded window = prefetch_batches of decoded samples
                # in flight while the accelerator consumes the current
                # batch
                stream = parallel_map_ordered(
                    self._load_one, order, self._workers,
                    window=batch * self._prefetch)
                buf_x, buf_y = [], []
                for xi, yi in stream:
                    buf_x.append(xi)
                    buf_y.append(yi)
                    if len(buf_x) == batch:
                        yb = None if self._labels is None \
                            else np.asarray(buf_y)
                        yield np.stack(buf_x), yb, batch
                        buf_x, buf_y = [], []
                # tail dropped: the jitted train step needs static shapes

        _Impl.__name__ = "ImageFolderDataset"
        _folder_dataset_cls = _Impl
    return _folder_dataset_cls(*args, **kwargs)
