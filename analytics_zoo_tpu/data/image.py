"""ImageSet + image preprocessing pipeline.

The reference's distributed image pipeline (`zoo/.../feature/image/
ImageSet.scala:368` + OpenCV-backed `ImageProcessing` transforms inherited
from BigDL: Resize/Crop/Normalize/Brightness/Flip, python mirrors
`pyzoo/zoo/feature/image/imagePreprocessing.py`). Same composable-transform
surface here over numpy/cv2 on the host; the output feeds the mesh as NHWC
float batches (TPU-native layout). Host-side augmentation parallelizes over
XShards; device-side normalization could fuse into the jit program but is
kept host-side for reference parity.
"""

from __future__ import annotations

import glob
import os
from typing import Any, Callable, List, Optional, Sequence, Tuple

import numpy as np

try:
    import cv2
    _HAS_CV2 = True
except ImportError:  # pragma: no cover - cv2 is present in the base image
    _HAS_CV2 = False


def _require_cv2():
    if not _HAS_CV2:
        raise ImportError(
            "opencv-python (cv2) is required for image decoding/resizing; "
            "it is unavailable in this environment")


def load_image(value) -> np.ndarray:
    """Image path or raw encoded bytes -> RGB HWC uint8 ndarray (the serving
    client's image ingestion; reference ships b64 JPEG, `client.py:114`)."""
    _require_cv2()
    if isinstance(value, (bytes, bytearray)):
        arr = cv2.imdecode(np.frombuffer(bytes(value), np.uint8),
                           cv2.IMREAD_COLOR)
    else:
        arr = cv2.imread(str(value))
    if arr is None:
        raise ValueError("Could not decode image input")
    return cv2.cvtColor(arr, cv2.COLOR_BGR2RGB)


class ImageProcessing:
    """Composable transform; `>>` or `chain` composes (the reference's
    `->` pipeline operator)."""

    def apply(self, img: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def __call__(self, img):
        return self.apply(img)

    def __rshift__(self, other: "ImageProcessing") -> "ChainedPreprocessing":
        return ChainedPreprocessing([self, other])


class ChainedPreprocessing(ImageProcessing):
    def __init__(self, transforms: Sequence[ImageProcessing]):
        self.transforms = list(transforms)

    def apply(self, img):
        for t in self.transforms:
            img = t.apply(img)
        return img

    def __rshift__(self, other):
        return ChainedPreprocessing(self.transforms + [other])


class ImageResize(ImageProcessing):
    """`ImageResize` (bilinear, W×H)."""

    def __init__(self, resize_h: int, resize_w: int):
        self.h, self.w = resize_h, resize_w

    def apply(self, img):
        _require_cv2()
        return cv2.resize(img, (self.w, self.h),
                          interpolation=cv2.INTER_LINEAR)


class ImageCenterCrop(ImageProcessing):
    def __init__(self, crop_h: int, crop_w: int):
        self.h, self.w = crop_h, crop_w

    def apply(self, img):
        H, W = img.shape[:2]
        if H < self.h or W < self.w:
            raise ValueError(f"Image {H}x{W} smaller than crop "
                             f"{self.h}x{self.w}")
        y0 = (H - self.h) // 2
        x0 = (W - self.w) // 2
        return img[y0:y0 + self.h, x0:x0 + self.w]


class ImageRandomCrop(ImageProcessing):
    def __init__(self, crop_h: int, crop_w: int, seed: Optional[int] = None):
        self.h, self.w = crop_h, crop_w
        self.rng = np.random.RandomState(seed)

    def apply(self, img):
        H, W = img.shape[:2]
        if H < self.h or W < self.w:
            raise ValueError(f"Image {H}x{W} smaller than crop "
                             f"{self.h}x{self.w}")
        y0 = self.rng.randint(0, H - self.h + 1)
        x0 = self.rng.randint(0, W - self.w + 1)
        return img[y0:y0 + self.h, x0:x0 + self.w]


class ImageHFlip(ImageProcessing):
    """Horizontal flip with probability p (`ImageHFlip`)."""

    def __init__(self, p: float = 0.5, seed: Optional[int] = None):
        self.p = p
        self.rng = np.random.RandomState(seed)

    def apply(self, img):
        if self.rng.rand() < self.p:
            return img[:, ::-1].copy()
        return img


class ImageBrightness(ImageProcessing):
    """Additive brightness jitter in [delta_low, delta_high]
    (`ImageBrightness`)."""

    def __init__(self, delta_low: float = -32.0, delta_high: float = 32.0,
                 seed: Optional[int] = None):
        self.low, self.high = delta_low, delta_high
        self.rng = np.random.RandomState(seed)

    def apply(self, img):
        return img.astype(np.float32) + self.rng.uniform(self.low, self.high)


class ImageChannelNormalize(ImageProcessing):
    """(x - mean) / std per channel (`ImageChannelNormalize`)."""

    def __init__(self, mean_r: float, mean_g: float, mean_b: float,
                 std_r: float = 1.0, std_g: float = 1.0, std_b: float = 1.0):
        self.mean = np.array([mean_r, mean_g, mean_b], np.float32)
        self.std = np.array([std_r, std_g, std_b], np.float32)

    def apply(self, img):
        return (img.astype(np.float32) - self.mean) / self.std


class ImageMatToTensor(ImageProcessing):
    """To float32; NHWC stays native (TPU conv layout) unless
    format='NCHW' requested (`ImageMatToTensor` toChw)."""

    def __init__(self, format: str = "NHWC"):
        self.format = format

    def apply(self, img):
        img = img.astype(np.float32)
        if self.format == "NCHW":
            return np.transpose(img, (2, 0, 1))
        return img


class ImageSet:
    """Collection of images + optional labels (`ImageSet.scala:368`
    read/transform surface), sharded like XShards."""

    def __init__(self, images: List[np.ndarray],
                 labels: Optional[np.ndarray] = None,
                 paths: Optional[List[str]] = None):
        self.images = images
        self.labels = labels
        self.paths = paths

    @staticmethod
    def read(path: str, with_label: bool = False,
             one_based_label: bool = True) -> "ImageSet":
        """Read image file/dir (optionally `dir/<class>/img.jpg` layout for
        labels, like `ImageSet.read` + label resolution)."""
        if os.path.isdir(path):
            files = sorted(glob.glob(os.path.join(path, "**", "*.*"),
                                     recursive=True))
            files = [f for f in files if f.rsplit(".", 1)[-1].lower() in
                     ("jpg", "jpeg", "png", "bmp")]
        else:
            files = [path]
        if not files:
            raise FileNotFoundError(f"No images under {path}")
        _require_cv2()
        images = [cv2.cvtColor(cv2.imread(f), cv2.COLOR_BGR2RGB)
                  for f in files]
        labels = None
        if with_label:
            classes = sorted({os.path.basename(os.path.dirname(f))
                              for f in files})
            base = 1 if one_based_label else 0
            cls_idx = {c: i + base for i, c in enumerate(classes)}
            labels = np.array([cls_idx[os.path.basename(os.path.dirname(f))]
                               for f in files], np.int32)
        return ImageSet(images, labels, files)

    def transform(self, transformer: ImageProcessing) -> "ImageSet":
        return ImageSet([transformer(im) for im in self.images],
                        self.labels, self.paths)

    def to_dataset(self, batch_size: int = -1, batch_per_thread: int = -1):
        from analytics_zoo_tpu.data.dataset import TPUDataset
        x = np.stack(self.images)
        return TPUDataset(x, self.labels, batch_size, batch_per_thread)

    def __len__(self):
        return len(self.images)
