"""FeatureSet — cached training data with pluggable memory tiers.

The reference's `FeatureSet` (`zoo/.../feature/FeatureSet.scala:643`)
caches the training RDD in DRAM, PMEM (via a JNI memkind allocator,
`pmem/PersistentMemoryAllocator.java:37`), or DISK_AND_DRAM with a
configurable DRAM slice (`FeatureSet.scala:662-692`). The TPU-host analogue:

- DRAM        — plain numpy arrays in host RAM (default);
- DISK        — numpy memmaps spilled to a cache dir; the OS page cache is
                the "DRAM portion" (this also covers the PMEM tier: memkind
                PMEM is exactly a file-backed mmap on fsdax);
- DISK_AND_DRAM(n) — first `n` percent pinned in RAM, rest memmapped
                (`DISK_AND_DRAM.numSlice` semantics).

Shuffle is index-level per epoch (cheap) rather than data movement.
"""

from __future__ import annotations

import os
import tempfile
from typing import Any, Dict, List, Optional, Sequence

import numpy as np


class FeatureSet:
    def __init__(self, data, memory_type: str = "DRAM",
                 cache_dir: Optional[str] = None):
        """data: pytree of ndarrays with a shared leading dim (or an XShards
        of such)."""
        import jax
        from analytics_zoo_tpu.data.shards import XShards
        if isinstance(data, XShards):
            data = data.to_numpy()
        self.memory_type = memory_type.upper()
        leaves, self._treedef = jax.tree_util.tree_flatten(data)
        if not leaves:
            raise ValueError("Empty FeatureSet")
        self._n = len(leaves[0])
        dram_fraction = 1.0
        if self.memory_type.startswith("DISK_AND_DRAM"):
            # DISK_AND_DRAM(n) → n percent DRAM (numSlice analogue)
            inside = self.memory_type[len("DISK_AND_DRAM"):].strip("()")
            dram_fraction = (int(inside) / 100.0) if inside else 0.5
        elif self.memory_type == "DISK":
            dram_fraction = 0.0
        elif self.memory_type in ("DRAM", "PMEM"):
            dram_fraction = 1.0
        else:
            raise ValueError(f"Unsupported memory_type: {memory_type}")

        self._split = int(self._n * dram_fraction)
        if self._split < self._n:
            # always a fresh private subdir: two FeatureSets sharing a
            # cache_dir must not truncate each other's live memmaps
            self._cache_dir = tempfile.mkdtemp(
                prefix="zoo_featureset_", dir=cache_dir)
            self._leaves = []
            for i, leaf in enumerate(leaves):
                arr = np.asarray(leaf)
                head = arr[:self._split].copy()
                path = os.path.join(self._cache_dir, f"leaf_{i}.npy")
                np.save(path, arr[self._split:])
                tail = np.load(path, mmap_mode="r")
                self._leaves.append((head, tail))
        else:
            self._leaves = [(np.asarray(l), None) for l in leaves]

    # -- data access -------------------------------------------------------
    def __len__(self):
        return self._n

    def take(self, idx: np.ndarray):
        """Gather rows by (possibly shuffled) indices into a pytree batch."""
        import jax
        out = []
        for head, tail in self._leaves:
            if tail is None:
                out.append(head[idx])
            else:
                in_head = idx < self._split
                rows = np.empty((len(idx),) + head.shape[1:], head.dtype)
                rows[in_head] = head[idx[in_head]]
                rows[~in_head] = tail[idx[~in_head] - self._split]
                out.append(rows)
        return jax.tree_util.tree_unflatten(self._treedef, out)

    def _native_loader(self, batch_size: int, drop_remainder: bool,
                       ordered: bool):
        """C++ threaded loader for this batch geometry. The dataset is
        packed ONCE per FeatureSet (streamed in chunks — never a full-RAM
        copy); per-geometry loaders share that file via mmap. `ordered`
        uses a single worker so batches arrive in index order (threaded
        delivery is completion-ordered)."""
        from analytics_zoo_tpu.data import native_loader as nl
        if not nl.available():
            return None
        if getattr(self, "_packed", None) is None:
            # stream the (possibly memmapped) leaves: head then tail chunks
            class _Concat:
                def __init__(self, head, tail):
                    self.head, self.tail = head, tail
                    self.shape = (len(head) + len(tail),) + head.shape[1:]
                    self.dtype = head.dtype

                def __len__(self):
                    return self.shape[0]

                def __getitem__(self, sl):
                    lo, hi = sl.start or 0, sl.stop
                    h = len(self.head)
                    if hi <= h:
                        return self.head[lo:hi]
                    if lo >= h:
                        return self.tail[lo - h:hi - h]
                    return np.concatenate(
                        [self.head[lo:], self.tail[:hi - h]])

            leaves = [head if tail is None else _Concat(head, tail)
                      for head, tail in self._leaves]
            self._packed = nl.NativeBatchLoader.pack_file(
                leaves, cache_dir=getattr(self, "_cache_dir", None))
        path, n, specs = self._packed
        key = (batch_size, drop_remainder, ordered)
        cache = getattr(self, "_native_cache", None)
        if cache is None:
            cache = self._native_cache = {}
        if key not in cache:
            cache[key] = nl.NativeBatchLoader(
                path, n, specs, batch_size,
                n_threads=1 if ordered else 2,
                drop_remainder=drop_remainder)
        return cache[key]

    def close(self):
        """Release native loaders and the packed record file."""
        for loader in getattr(self, "_native_cache", {}).values():
            loader.close()
        self._native_cache = {}
        packed = getattr(self, "_packed", None)
        if packed is not None and os.path.exists(packed[0]):
            os.unlink(packed[0])
        self._packed = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass

    def iter_batches(self, batch_size: int, shuffle: bool = True,
                     seed: int = 0, drop_remainder: bool = True,
                     native: Optional[bool] = None,
                     pipeline_workers: Optional[int] = None):
        """`native=None` auto-selects: spilled tiers go through the C++
        threaded loader (batch assembly off the GIL, overlapping the TPU
        step); DRAM stays on the numpy fast path. shuffle=False keeps the
        sequential-order contract (single-worker native delivery).
        `pipeline_workers` (default `ZooConfig.pipeline_workers` / env
        ZOO_PIPELINE_WORKERS) assembles the python-path batches on the
        shared input-pipeline worker pool instead: the per-epoch index
        permutation is fixed up front by `seed`, each index-batch
        gathers on a worker, and the reorder buffer emits batches in
        permutation order — identical batches at any worker count,
        bounded to `workers + 1` resident gathers."""
        import jax
        if native is None:
            native = self._split < self._n
        if native:
            loader = self._native_loader(batch_size, drop_remainder,
                                         ordered=not shuffle)
            if loader is not None:
                for leaves in loader.iter_epoch(seed=seed, shuffle=shuffle):
                    yield jax.tree_util.tree_unflatten(self._treedef, leaves)
                return
        idx = np.arange(self._n)
        if shuffle:
            np.random.RandomState(seed).shuffle(idx)
        nb = self._n // batch_size if drop_remainder \
            else -(-self._n // batch_size)
        sels = [idx[b * batch_size:(b + 1) * batch_size] for b in range(nb)]
        sels = [s for s in sels
                if len(s) == batch_size or not drop_remainder]
        from analytics_zoo_tpu.data.pipeline import (ShardPipeline,
                                                     resolve_workers)
        workers = resolve_workers(pipeline_workers)
        if workers > 1 and len(sels) > 1:
            pipe = ShardPipeline(sels, lambda sel: [self.take(sel)],
                                 workers=workers,
                                 label_fn=lambda s: "featureset batch")
            try:
                yield from pipe.samples()
            finally:
                pipe.close()
            return
        for sel in sels:
            yield self.take(sel)

    def to_dataset(self, batch_size: int = -1, batch_per_thread: int = -1):
        """DRAM tier materializes; spilled tiers wrap lazily so the DISK
        design survives the dataset bridge (no full-RAM gather)."""
        from analytics_zoo_tpu.data.dataset import (TPUDataset,
                                                    _FeatureSetDataset)
        if self._split == self._n:
            full = self.take(np.arange(self._n))
            if isinstance(full, dict) and "x" in full:
                return TPUDataset(full["x"], full.get("y"), batch_size,
                                  batch_per_thread)
            return TPUDataset(full, None, batch_size, batch_per_thread)
        return _FeatureSetDataset(self, batch_size, batch_per_thread)

    def __repr__(self):
        return (f"FeatureSet(n={self._n}, memory_type={self.memory_type}, "
                f"dram_rows={self._split})")
