"""Per-op attribution for the seq-2048 flash BERT step (VERDICT r4 #5).

    python scripts/profile_longseq.py [--batch 16] [--steps 8]
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile
import time
from collections import defaultdict

os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if ("JAX_DEFAULT_PRNG_IMPL" not in os.environ
        and jax.default_backend() == "tpu"):
    jax.config.update("jax_default_prng_impl", "rbg")

import numpy as np

from profile_ncf import parse_xplane  # shared xplane recipe


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--no-flash", action="store_true")
    args = ap.parse_args()

    from analytics_zoo_tpu import init_orca_context

    init_orca_context(cluster_mode="local")
    dev = jax.devices()[0]

    # warm once via the bench helper, then trace one fit epoch
    import optax
    from analytics_zoo_tpu.learn.estimator import Estimator
    from analytics_zoo_tpu.models.bert import BERTClassifier
    from analytics_zoo_tpu.ops import objectives

    model = BERTClassifier(
        num_classes=2, vocab=30522, hidden_size=768, n_block=12, n_head=12,
        seq_len=args.seq, intermediate_size=3072,
        use_flash=not args.no_flash, remat=False,
        stacked=os.environ.get("PROF_STACKED", "0") == "1")
    est = Estimator.from_keras(
        model, optimizer=optax.adamw(1e-4),
        loss=objectives.get("sparse_categorical_crossentropy",
                            from_logits=True))
    rs = np.random.RandomState(0)
    n = args.batch * args.steps
    data = {"x": [rs.randint(0, 30522, (n, args.seq)).astype(np.int32),
                  np.ones((n, args.seq), np.float32)],
            "y": rs.randint(0, 2, (n,)).astype(np.int32)}
    fit_kw = dict(epochs=1, batch_size=args.batch,
                  steps_per_run=args.steps, mixed_precision=True,
                  fused_optimizer=os.environ.get("PROF_FUSED", "0") == "1")
    est.fit(data, **fit_kw)

    trace_dir = tempfile.mkdtemp(prefix="longseq_prof_")
    jax.profiler.start_trace(trace_dir)
    t0 = time.perf_counter()
    est.fit(data, **fit_kw)
    wall = time.perf_counter() - t0
    jax.profiler.stop_trace()

    per_op = parse_xplane(trace_dir)
    total = sum(per_op.values())
    steps = args.steps

    def cat(name):
        n_ = name.lower()
        if "tpu_custom_call" in n_ or "custom-call" in n_:
            return "pallas-kernels"
        if "rng" in n_:
            return "rng"
        if "convolution" in n_ or "dot" in n_:
            return "matmul"
        if "fusion" in n_:
            return "fusion"
        if "copy" in n_ or "transpose" in n_ or "reshape" in n_:
            return "data-movement"
        return "other"

    cats = defaultdict(float)
    for name, s in per_op.items():
        cats[cat(name)] += s
    print(f"\nwall {wall*1e3:.0f} ms  device {total*1e3:.0f} ms  "
          f"steps {steps}  device/step {total/steps*1e3:.1f} ms")
    for c, s in sorted(cats.items(), key=lambda kv: -kv[1]):
        print(f"  {c:16s} {s/steps*1e3:8.2f} ms/step ({100*s/total:5.1f}%)")
    print("\ntop ops (ms/step):")
    for name, s in sorted(per_op.items(), key=lambda kv: -kv[1])[:40]:
        print(f"  {s/steps*1e3:8.2f}  {name[:120]}")

    # group by op-name base (strip %, trailing .NNN and shape suffix) so
    # the long tail of per-tensor fusions becomes visible
    import re
    groups = defaultdict(lambda: [0.0, 0])
    for name, s in per_op.items():
        base = name.split(" = ")[0].strip().lstrip("%")
        base = re.sub(r"[.\d]+$", "", base)
        groups[base][0] += s
        groups[base][1] += 1
    print("\nop groups (ms/step, count):")
    for base, (s, c) in sorted(groups.items(), key=lambda kv: -kv[1][0])[:30]:
        print(f"  {s/steps*1e3:8.2f}  x{c:4d}  {base[:90]}")


if __name__ == "__main__":
    main()
