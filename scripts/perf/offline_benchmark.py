"""Offline serving throughput benchmark.

The reference's `docker/cluster-serving/perf/offline-benchmark` +
`cluster-serving-enqueue-test` recipe: enqueue 10k images, read
throughput from the serving log. Here the whole harness is one script:
stand up the RESP2 stream server + batched serving loop, enqueue N
images through the client API, wait for drain, print ONE JSON line with
end-to-end throughput and the serving-side timer stats.

    python scripts/perf/offline_benchmark.py                # 10k images
    python scripts/perf/offline_benchmark.py --n 500 --broker memory
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--n", type=int, default=10_000,
                   help="images to enqueue (reference uses 10000)")
    p.add_argument("--broker", choices=("redis", "memory"),
                   default="redis")
    p.add_argument("--image-size", type=int, default=32)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--timeout-s", type=float, default=600.0)
    args = p.parse_args(argv)

    from analytics_zoo_tpu import init_orca_context
    from analytics_zoo_tpu.keras import Sequential
    from analytics_zoo_tpu.keras import layers as L
    from analytics_zoo_tpu.serving import (ClusterServing, InferenceModel,
                                           InputQueue, MemoryBroker,
                                           OutputQueue)

    init_orca_context(cluster_mode="local")
    S = args.image_size
    model = Sequential([
        L.Convolution2D(16, 3, 3, input_shape=(S, S, 3),
                        border_mode="same", activation="relu"),
        L.GlobalAveragePooling2D(),
        L.Dense(10, activation="softmax"),
    ])
    model.ensure_built(np.zeros((1, S, S, 3), np.float32))
    infer = InferenceModel(concurrent_num=2).load_keras(model)
    for b in (1, args.batch_size):
        infer.predict(np.zeros((b, S, S, 3), np.float32))  # warm buckets

    server = None
    if args.broker == "redis":
        from analytics_zoo_tpu.serving import MiniRedisServer, RedisBroker
        server = MiniRedisServer().start()
        serve_broker = RedisBroker(server.host, server.port)
        client_broker = RedisBroker(server.host, server.port)
    else:
        serve_broker = client_broker = MemoryBroker()

    serving = ClusterServing(infer, broker=serve_broker,
                             batch_size=args.batch_size,
                             batch_timeout_ms=5).start()
    inq = InputQueue(client_broker)
    outq = OutputQueue(client_broker)

    img = np.random.rand(S, S, 3).astype(np.float32)
    t0 = time.perf_counter()
    uris = [inq.enqueue(t=img) for _ in range(args.n)]
    t_enq = time.perf_counter() - t0
    print(f"{args.n} images enqueued in {t_enq:.1f}s", file=sys.stderr)

    # drain: wait until the LAST uri has a result, then count them all
    deadline = time.time() + args.timeout_s
    while time.time() < deadline:
        if outq.query(uris[-1]) is not None:
            break
        time.sleep(0.05)
    else:
        raise TimeoutError("serving did not drain the queue in time")
    t_total = time.perf_counter() - t0
    served = sum(1 for u in uris if outq.query(u) is not None)

    metrics = serving.metrics()
    serving.stop()
    if server is not None:
        server.stop()

    print(json.dumps({
        "metric": "serving_offline_throughput",
        "value": round(served / t_total, 1),
        "unit": "images/s",
        "broker": args.broker,
        "n_enqueued": args.n,
        "n_served": served,
        "wall_s": round(t_total, 2),
        "enqueue_s": round(t_enq, 2),
        "serving_metrics": metrics,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
