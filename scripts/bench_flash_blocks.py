"""A/B flash-attention fwd+bwd at a given tile shape on the real chip.

    python scripts/bench_flash_blocks.py <block_q> <block_k> [rate]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

if jax.default_backend() == "tpu":
    jax.config.update("jax_default_prng_impl", "rbg")

from analytics_zoo_tpu.pallas.flash_attention import flash_attention


def main():
    bq, bk = int(sys.argv[1]), int(sys.argv[2])
    rate = float(sys.argv[3]) if len(sys.argv) > 3 else 0.1
    B, H, T, D = 16, 12, 2048, 64
    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.randn(B, H, T, D), jnp.bfloat16)
    k = jnp.asarray(rs.randn(B, H, T, D), jnp.bfloat16)
    v = jnp.asarray(rs.randn(B, H, T, D), jnp.bfloat16)

    def loss(q, k, v):
        o = flash_attention(q, k, v, dropout_rate=rate,
                            dropout_seed=7, block_q=bq, block_k=bk,
                            bwd_block_q=bq, bwd_block_k=bk)
        return jnp.sum(o.astype(jnp.float32))

    iters = 10

    def step(i, carry):
        acc, = carry
        gq, gk, gv = jax.grad(loss, argnums=(0, 1, 2))(
            q + (acc * 1e-20).astype(q.dtype), k, v)
        # consume ALL grads: with gq alone, XLA dead-code-eliminates the
        # separate dk/dv pallas_call and the two-kernel backward times
        # only its dq half (round-5 finding — made the fused kernel look
        # slower than the pair at equal tiles when it wasn't)
        return (acc + jnp.sum(gq.astype(jnp.float32))
                + jnp.sum(gk.astype(jnp.float32))
                + jnp.sum(gv.astype(jnp.float32)),)

    run = jax.jit(
        lambda: jax.lax.fori_loop(0, iters, step, (jnp.float32(0),))[0])
    float(run())
    best = float("inf")
    for _ in range(4):
        t0 = time.perf_counter()
        float(run())
        best = min(best, time.perf_counter() - t0)
    print(f"RESULT blocks {bq}x{bk} rate {rate}: "
          f"{best / iters * 1e3:.2f} ms per fwd+bwd")


if __name__ == "__main__":
    main()
