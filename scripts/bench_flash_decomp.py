"""Decompose flash fwd vs bwd cost per (fwd_block, bwd_block) combo.

At dropout rate 0 the fwd/bwd tilings decouple, so this isolates where
the backward time goes and whether the fused bwd kernel wins at shapes
the dropout-coupled path cannot reach today.

    python scripts/bench_flash_decomp.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

if jax.default_backend() == "tpu":
    jax.config.update("jax_default_prng_impl", "rbg")

from analytics_zoo_tpu.pallas.flash_attention import flash_attention


def timeit(run, iters):
    float(run())
    best = float("inf")
    for _ in range(4):
        t0 = time.perf_counter()
        float(run())
        best = min(best, time.perf_counter() - t0)
    return best / iters * 1e3


def main():
    rate = float(sys.argv[1]) if len(sys.argv) > 1 else 0.0
    B, H, T, D = 16, 12, 2048, 64
    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.randn(B, H, T, D), jnp.bfloat16)
    k = jnp.asarray(rs.randn(B, H, T, D), jnp.bfloat16)
    v = jnp.asarray(rs.randn(B, H, T, D), jnp.bfloat16)
    iters = 10

    def fwd_only(bq, bk):
        def f():
            def body(i, acc):
                o = flash_attention(q + (acc * 1e-20).astype(q.dtype), k, v,
                                    dropout_rate=rate, dropout_seed=7,
                                    block_q=bq, block_k=bk)
                return acc + jnp.sum(o.astype(jnp.float32))
            return jax.lax.fori_loop(0, iters, body, jnp.float32(0))
        return timeit(jax.jit(f), iters)

    def fwd_bwd(bq, bk, bbq, bbk):
        def loss(q, k, v):
            o = flash_attention(q, k, v, dropout_rate=rate, dropout_seed=7,
                                block_q=bq, block_k=bk,
                                bwd_block_q=bbq, bwd_block_k=bbk)
            return jnp.sum(o.astype(jnp.float32))

        def f():
            def body(i, acc):
                # consume ALL grads: with gq alone, XLA dead-code-
                # eliminates the separate dk/dv pallas_call and the
                # two-kernel path times only HALF its backward
                gq, gk, gv = jax.grad(loss, argnums=(0, 1, 2))(
                    q + (acc * 1e-20).astype(q.dtype), k, v)
                return (acc + jnp.sum(gq.astype(jnp.float32))
                        + jnp.sum(gk.astype(jnp.float32))
                        + jnp.sum(gv.astype(jnp.float32)))
            return jax.lax.fori_loop(0, iters, body, jnp.float32(0))
        return timeit(jax.jit(f), iters)

    for bq, bk in [(1024, 1024), (1024, 512)]:
        print(f"fwd-only {bq}x{bk} rate {rate}: {fwd_only(bq, bk):.2f} ms",
              flush=True)
    combos = [
        (1024, 1024, 1024, 1024),   # bwd: two-kernel
        (1024, 1024, 1024, 512),    # bwd: fused (n_kb=4, 512k tile)
        (1024, 1024, 512, 512),     # bwd: fused small
        (1024, 1024, 2048, 512),    # bwd: gated? n_kb=4 but 1M tile -> pair
    ]
    for bq, bk, bbq, bbk in combos:
        try:
            ms = fwd_bwd(bq, bk, bbq, bbk)
            print(f"fwd {bq}x{bk} + bwd {bbq}x{bbk} rate {rate}: "
                  f"{ms:.2f} ms", flush=True)
        except Exception as e:  # noqa: BLE001
            print(f"fwd {bq}x{bk} + bwd {bbq}x{bbk}: FAILED "
                  f"{type(e).__name__}: {str(e)[:120]}", flush=True)


if __name__ == "__main__":
    main()
