#!/usr/bin/env python
"""Static lint for the GSPMD sharding rule table (ISSUE 12 satellite;
tier-1 via tests/test_sharding_rules.py).

The rule table (`parallel/sharding.ShardingRules`) is the ONE layout
contract shared by the sharded fit, serving's sharded placement, the
checkpoint gather/restore paths and the compile-cache key — a rule that
names a nonexistent mesh axis, or carries a spec whose rank disagrees
with the parameters it matches, fails silently at placement time
(`_trim_spec` drops what it cannot apply) and quietly replicates state
the operator believes is sharded. This lint makes those failures loud
at CI time:

- **axis vocabulary**: every axis a rule names must be a real mesh axis
  (`common/mesh.AXIS_NAMES`) AND appear in at least one SUPPORTED mesh
  factorization — the (data×fsdp) and (data×fsdp×tensor) meshes the
  trainer and serving actually build — so a rule can never demand a
  placement no supported mesh supplies;
- **rank consistency**: against a canonical parameter catalog (a real
  BERT build, unstacked and stacked, plus the task-head kernels), every
  rule's spec must have rank <= every matched parameter's rank, and a
  FULL-rank spec on each matched kernel (a 3-entry spec on a 2-D kernel
  would silently truncate);
- **liveness**: every rule must match at least one catalog parameter —
  a dead rule is a renamed parameter waiting to replicate.

Exit 0 when clean; 1 with one line per violation.

    python scripts/check_sharding_rules.py
"""

from __future__ import annotations

import os
import sys
from typing import List, Optional, Sequence, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The mesh factorizations the stack actually constructs: fit_keras /
# serving default (data×fsdp) and the big-model frontier's
# (data×fsdp×tensor). An axis outside their union has no supported mesh
# to exist on, so a rule naming it could never engage.
SUPPORTED_FACTORIZATIONS: Tuple[Tuple[str, ...], ...] = (
    ("data", "fsdp"),
    ("data", "fsdp", "tensor"),
)


def build_catalog() -> List[Tuple[str, Tuple[int, ...]]]:
    """Canonical (path, shape) parameter catalog the rules are written
    against: one real BERT build (the transformer layer library's own
    names), its stacked-encoder form ([L, in, out] leaves), and the
    BERT task-model head kernels quantization/serving also touch."""
    import jax

    from analytics_zoo_tpu.keras.transformer import (BERT,
                                                     stack_block_params)
    from analytics_zoo_tpu.parallel.sharding import _tree_paths_and_leaves

    bert = BERT(vocab=32, hidden_size=16, n_block=2, n_head=2,
                seq_len=8, intermediate_size=32, pooled_only=True,
                name="bert")
    params = bert.build(jax.random.PRNGKey(0), (None, 8))
    stacked = stack_block_params(dict(params), 2, "bert")
    cat = []
    for prefix, tree in (("bert", params), ("bert_stacked", stacked)):
        cat.extend((f"{prefix}/{p}", tuple(map(int, __import__(
            "numpy").shape(l))))
            for p, l in _tree_paths_and_leaves(tree))
    cat.extend([("cls_kernel", (16, 2)), ("ner_kernel", (16, 4)),
                ("qa_kernel", (16, 2))])
    return cat


def check_rules(rules=None, catalog=None,
                factorizations: Sequence[Sequence[str]] = None
                ) -> List[str]:
    """Lint one rule table; returns a list of violation strings."""
    from analytics_zoo_tpu.common.mesh import AXIS_NAMES
    from analytics_zoo_tpu.parallel.sharding import TRANSFORMER_RULES

    rules = rules if rules is not None else TRANSFORMER_RULES
    catalog = catalog if catalog is not None else build_catalog()
    factorizations = factorizations or SUPPORTED_FACTORIZATIONS
    supported_axes = {a for f in factorizations for a in f}
    errors: List[str] = []

    for pat, spec in rules.rules:
        where = f"rule {pat.pattern!r} -> {spec}"
        # -- axis vocabulary ---------------------------------------------
        for entry in spec:
            axes = entry if isinstance(entry, tuple) else (entry,)
            for ax in axes:
                if ax is None:
                    continue
                if ax not in AXIS_NAMES:
                    errors.append(
                        f"{where}: axis {ax!r} is not a mesh axis "
                        f"(common/mesh.AXIS_NAMES = {list(AXIS_NAMES)})")
                elif ax not in supported_axes:
                    errors.append(
                        f"{where}: axis {ax!r} exists on no supported "
                        f"mesh factorization {factorizations} — the "
                        "rule could never engage")
        # -- rank consistency + liveness ---------------------------------
        matched = [(p, s) for p, s in catalog if pat.search(p)]
        if not matched:
            errors.append(
                f"{where}: matches no parameter in the canonical "
                "catalog (dead rule — renamed parameter silently "
                "falling through to the fsdp/replicate fallback?)")
        for path, shape in matched:
            if len(spec) > len(shape):
                errors.append(
                    f"{where}: spec rank {len(spec)} exceeds matched "
                    f"parameter {path} rank {len(shape)} — the extra "
                    "axes silently drop at placement time")
            sharded_axes = sum(1 for e in spec if e is not None)
            if len(shape) >= 2 and sharded_axes and len(spec) > 0 \
                    and len(spec) < len(shape) - 1:
                # a 2-D+ kernel matched by a 1-entry sharding spec
                # leaves trailing dims implicitly replicated; only the
                # FINAL dims may be elided (PartitionSpec semantics),
                # so a spec shorter than rank-1 on a kernel is a smell
                errors.append(
                    f"{where}: spec rank {len(spec)} leaves "
                    f"{len(shape) - len(spec)} trailing dim(s) of "
                    f"{path} {shape} implicitly replicated — spell "
                    "them (P(..., None)) so the layout is explicit")
    return errors


def main(argv=None) -> int:
    errors = check_rules()
    for e in errors:
        print(e)
    if errors:
        print(f"{len(errors)} sharding-rule violation(s)")
        return 1
    from analytics_zoo_tpu.parallel.sharding import TRANSFORMER_RULES
    print(f"sharding rules OK ({len(TRANSFORMER_RULES.rules)} rules "
          f"checked against {len(build_catalog())} catalog parameters)")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
