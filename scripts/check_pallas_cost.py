#!/usr/bin/env python
"""Static lint: every `pallas_call` in the package must carry a
`cost_estimate` (ISSUE 9 satellite; tier-1 via
tests/test_fused_optimizer.py).

XLA's HLO cost analysis cannot see inside a Pallas custom call — a
Mosaic kernel reports ~0 FLOPs/bytes — so the roofline layer
(`observability/roofline.py`, `trainer._StepCostTracker`) depends on
each kernel declaring its analytic cost via
`pl.CostEstimate(flops=..., bytes_accessed=..., ...)`. A kernel shipped
without one silently blinds the MFU/HBM-utilization gauges for every
program that embeds it; this lint turns that into a CI failure instead.

Checked statically over the whole `analytics_zoo_tpu/` package: each
`pallas_call(` call expression (nested parens respected, multi-line
included) must contain a `cost_estimate=` keyword. A call may opt out
with a trailing `# pallas-cost-ok: <reason>` comment on the
`pallas_call(` line; the reason is mandatory so the waiver documents
itself.

    python scripts/check_pallas_cost.py [repo_root]
"""

from __future__ import annotations

import os
import re
import sys
from typing import List

PKG = "analytics_zoo_tpu"

# no \s* before the paren: prose like "pallas_call (Mosaic reports ~0)"
# in docstrings/comments must not match
CALL_RE = re.compile(r"\bpallas_call\(")
ALLOW_RE = re.compile(r"#\s*pallas-cost-ok:\s*\S")
COST_RE = re.compile(r"\bcost_estimate\s*=")


def _call_slice(src: str, open_paren: int) -> str:
    """The argument text of the call whose '(' sits at `open_paren`,
    respecting nested parens/brackets (multi-line calls included)."""
    depth = 0
    for i in range(open_paren, len(src)):
        c = src[i]
        if c in "([{":
            depth += 1
        elif c in ")]}":
            depth -= 1
            if depth == 0:
                return src[open_paren + 1:i]
    return src[open_paren + 1:]


def _line_of(src: str, pos: int) -> int:
    return src.count("\n", 0, pos) + 1


def _line_text(src: str, pos: int) -> str:
    start = src.rfind("\n", 0, pos) + 1
    end = src.find("\n", pos)
    return src[start:end if end != -1 else len(src)]


def check_file(path: str) -> List[str]:
    with open(path, encoding="utf-8") as fh:
        src = fh.read()
    errors = []
    for m in CALL_RE.finditer(src):
        # the returned transform is CALLED with operands right after
        # `pallas_call(...)` — the kwargs live in the FIRST paren group
        args = _call_slice(src, m.end() - 1)
        if COST_RE.search(args):
            continue
        if ALLOW_RE.search(_line_text(src, m.start())):
            continue
        errors.append(
            f"{path}:{_line_of(src, m.start())}: pallas_call without a "
            "cost_estimate= (roofline gauges go blind for any program "
            "embedding this kernel; add pl.CostEstimate(...) or a "
            "'# pallas-cost-ok: <reason>' waiver)")
    return errors


# Presence manifest (ISSUE 19 satellite): kernels the roofline layer
# KNOWS about must keep at least this many costed `pallas_call` sites
# in place — decode_attention carries TWO (the contiguous decode-step
# kernel and the paged block-table kernel), so a refactor that drops
# one (or moves it somewhere the analytic cost no longer reaches)
# fails CI instead of silently zeroing that kernel's roofline bytes.
EXPECTED_MIN_CALLS = {
    os.path.join("pallas", "decode_attention.py"): 2,
    os.path.join("pallas", "flash_attention.py"): 1,
    os.path.join("pallas", "fused_adam.py"): 1,
    os.path.join("pallas", "dropout.py"): 1,
    os.path.join("pallas", "segment_update.py"): 1,
}


def check(root: str = ".") -> List[str]:
    errors: List[str] = []
    pkg = os.path.join(root, PKG)
    counts = {}
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for name in sorted(filenames):
            if name.endswith(".py"):
                path = os.path.join(dirpath, name)
                errors.extend(check_file(path))
                with open(path, encoding="utf-8") as fh:
                    counts[os.path.relpath(path, pkg)] = len(
                        CALL_RE.findall(fh.read()))
    for rel, want in sorted(EXPECTED_MIN_CALLS.items()):
        have = counts.get(rel, 0)
        if have < want:
            errors.append(
                f"{os.path.join(pkg, rel)}: expected >= {want} "
                f"pallas_call site(s), found {have} (a known kernel "
                "went missing — update EXPECTED_MIN_CALLS if this is "
                "an intentional removal)")
    return errors


def main(argv: List[str]) -> int:
    root = argv[1] if len(argv) > 1 else "."
    errors = check(root)
    for e in errors:
        print(e)
    if errors:
        print(f"{len(errors)} pallas_call(s) without cost_estimate")
        return 1
    print("pallas cost-estimate lint: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
