#!/usr/bin/env python
"""Static lint for metric names (ISSUE 2 satellite; tier-1 via
tests/test_metric_names.py).

Scans every Python source under `analytics_zoo_tpu/` (plus the bench
scripts) for literal registry registrations —
`<registry>.counter("name", ...)`, `.gauge(...)`, `.histogram(...)` —
and enforces the conventions the runtime registry also checks, so a
violation fails CI before it ever runs:

- names are snake_case: `[a-z][a-z0-9]*(_[a-z0-9]+)*`
- unit-suffix conventions: counters end `_total`; histograms end with a
  unit (`_ms`, `_bytes`, `_seconds`); gauges must NOT claim `_total`
- unique registration: one name maps to exactly one metric kind across
  the whole codebase (get-or-create from several sites is fine — that
  is the convergence the registry exists for — but the same name as
  both a counter and a gauge is a collision Prometheus would reject)
- docs drift (ISSUE 6 satellite): every REQUIRED family must appear in
  `docs/ProgrammingGuide/observability.md`, so a new load-bearing
  family (profiler, SLO, memory, roofline) cannot ship undocumented

Exit code 0 when clean; 1 with one line per violation otherwise.

    python scripts/check_metric_names.py [root ...]
"""

from __future__ import annotations

import os
import re
import sys
from typing import Dict, List, Tuple

NAME_RE = re.compile(r"^[a-z][a-z0-9]*(_[a-z0-9]+)*$")
# `registry.counter("x"` / `reg.gauge('y'` / `.histogram("z"` — literal
# first argument only; dynamically-built names are the runtime
# registry's job
CALL_RE = re.compile(
    r"\.\s*(counter|gauge|histogram)\s*\(\s*(?:\n\s*)?['\"]([^'\"]+)['\"]",
    re.MULTILINE)

COUNTER_SUFFIX = ("_total",)
HIST_SUFFIXES = ("_ms", "_bytes", "_seconds")

DEFAULT_ROOTS = ("analytics_zoo_tpu", "scripts", "bench_serving.py",
                 "bench.py", "bench_ncf.py")

# Load-bearing names with their required kinds: families other code
# (dashboards, the bench JSON, docs tables) depends on existing. A
# rename or kind change here must fail CI, not silently break scrapes.
# Unit semantics ride on the suffix conventions checked above
# (`_total` counters, `_ms`/`_bytes`-suffixed histograms).
REQUIRED = {
    "compile_cache_hits_total": "counter",
    "compile_cache_misses_total": "counter",
    "compile_cache_load_ms": "histogram",
    "compile_cache_compile_ms": "histogram",
    "compile_cache_bytes": "gauge",
    "serving_records_total": "counter",
    "serving_stage_ms": "histogram",
    "training_steps_total": "counter",
    # fault-tolerance layer (ISSUE 5): the failure-matrix metrics the
    # docs table and the chaos bench read
    "serving_replica_quarantined_total": "counter",
    "serving_replica_revivals_total": "counter",
    "serving_broker_breaker_state": "gauge",
    "training_resumes_total": "counter",
    "training_step_retries_total": "counter",
    # deep-profiling layer (ISSUE 6): roofline accounting, on-demand
    # capture, device-memory telemetry, SLO health — the families the
    # bench JSON, /healthz, and the docs tables read
    "roofline_flops_total": "counter",
    "roofline_hbm_bytes_total": "counter",
    "roofline_achieved_tflops": "gauge",
    "roofline_achieved_hbm_gbps": "gauge",
    "roofline_mfu": "gauge",
    "roofline_hbm_utilization": "gauge",
    "profile_captures_total": "counter",
    "device_memory_live_bytes": "gauge",
    "device_memory_peak_bytes": "gauge",
    "slo_burn_rate": "gauge",
    "slo_met": "gauge",
    "observability_gauge_errors_total": "counter",
    # fused optimizer kernels (ISSUE 9): the A/B lever bench_ncf and
    # the roofline docs read, plus the roofline counters the fused-step
    # correction feeds (already REQUIRED above) — renaming any of these
    # silently blinds the NCF bound tracking
    "training_fused_update_ms": "histogram",
    "roofline_busy_seconds_total": "counter",
    # fleet scale-out (ISSUE 10): the families the fleet gateway's
    # /healthz contract, the fleet bench, and the redelivery zero-loss
    # accounting read — renaming any of these silently blinds the
    # fleet dashboard and the drain-curve JSON
    "serving_engines_alive": "gauge",
    "serving_engines_total": "counter",
    "serving_engine_heartbeats_total": "counter",
    "serving_claimed_records_total": "counter",
    # elastic serving (ISSUE 11): the adaptive-batching cost model and
    # controller telemetry, tiered admission outcomes, and autoscaler
    # state — the families the elastic bench JSON, the docs tables, and
    # any capacity dashboard read
    "serving_bucket_ms": "histogram",
    "serving_bucket_cost_ms": "gauge",
    "serving_queue_age_ms": "histogram",
    "serving_chosen_bucket_total": "counter",
    "serving_admission_total": "counter",
    "serving_backlog_depth": "gauge",
    "serving_engines_target": "gauge",
    "serving_autoscaler_decisions_total": "counter",
    # generative serving (ISSUE 18): per-token telemetry from the
    # continuous-batching decode engine — tokens throughput, the two
    # streaming SLO inputs (TTFT, inter-token latency), and the KV slot
    # occupancy gauge that drives admission
    "serving_tokens_total": "counter",
    "serving_ttft_ms": "histogram",
    "serving_itl_ms": "histogram",
    "serving_kv_slots_in_use": "gauge",
    # paged KV + prefix cache + chunked prefill (ISSUE 19): the block-
    # pool occupancy gauge that replaces the slot gauge as the paged
    # admission signal, the cache hit-rate pair, and the chunk counter
    # the ITL-protection accounting reads — renaming any of these
    # silently blinds the paged bench JSON and the docs tables
    "serving_kv_blocks_in_use": "gauge",
    "serving_prefix_cache_hits_total": "counter",
    "serving_prefix_cache_misses_total": "counter",
    "serving_prefill_chunks_total": "counter",
    # big-model frontier (ISSUE 12): quantized serving + tensor-parallel
    # placement telemetry — the families the int8 A/B bench, the docs
    # tables and any capacity dashboard read. serving_weight_bytes is
    # the honest per-dtype weight price (int8 reads ~4x under f32);
    # training_mesh_axis_size distinguishes a pure-fsdp fit from a
    # tensor-parallel one on a scrape.
    "serving_weight_bytes": "gauge",
    "training_mesh_axis_size": "gauge",
    "quantized_checkpoints_total": "counter",
    # zero-downtime rollout (ISSUE 14): the version lifecycle families
    # the /rollout endpoints, the chaos-rollout bench JSON, and the
    # fleet-convergence dashboard read — serving_model_version is how
    # a scrape watches a rollout sweep the fleet, and renaming any of
    # these silently blinds the rollback/quarantine audit trail
    "serving_model_version": "gauge",
    "serving_rollout_state": "gauge",
    "serving_rollout_transitions_total": "counter",
    "serving_rollout_rollbacks_total": "counter",
    # parallel input pipeline (ISSUE 15): the device-wait vs host-wait
    # accounting the input-pipeline bench A/B and the distributed-
    # training guide's "am I input-bound" runbook read — renaming
    # either silently blinds the input-stall verdict
    "training_input_wait_ms": "histogram",
    "training_input_bound": "gauge",
    # partitioned request plane + replicated gateway (ISSUE 16): the
    # per-partition ownership/churn families the request-plane guide's
    # runbook and the partition-scaling bench JSON read, plus the
    # gateway leader-election telemetry — renaming any of these blinds
    # the takeover audit trail a kill-the-leader drill depends on
    "serving_partitions_owned": "gauge",
    "serving_partition_lease_changes_total": "counter",
    "serving_partition_depth": "gauge",
    "gateway_role": "gauge",
    "gateway_leader_changes_total": "counter",
    # fleet observability plane (ISSUE 17): the trace-export health
    # families and the fleet-scrape staleness gauge — the guards that
    # make span loss and stale engine blobs visible on a scrape.
    # Renaming any of these blinds the trace plane's own telemetry.
    "observability_spans_dropped_total": "counter",
    "serving_trace_spans_total": "counter",
    "serving_trace_sampled_total": "counter",
    "serving_trace_dropped_total": "counter",
    "fleet_scrape_age_s": "gauge",
    # crash-safe generative serving (ISSUE 20): the recovery/preemption
    # audit trail the chaos bench JSON and the fault-tolerance docs
    # matrix read — renaming any of these silently blinds the
    # zero-token-loss accounting
    "serving_decode_resumes_total": "counter",
    "serving_preemptions_total": "counter",
    "serving_sequence_aborts_total": "counter",
    "serving_token_replays_total": "counter",
    "serving_kv_pressure_evictions_total": "counter",
}

OBSERVABILITY_DOC = os.path.join("docs", "ProgrammingGuide",
                                 "observability.md")

# Serving span-name vocabulary (ISSUE 17): the cross-process trace
# assembler keys its skew model and critical-path columns on these
# literal stage names, so a misspelled span silently falls out of
# /trace/<id>/summary. REQUEST_SPANS must carry a trace_id/trace_ids so
# the request's merged timeline can find them; LIFECYCLE_SPANS are
# engine-scoped events that legitimately have no request id.
REQUEST_SPANS = frozenset({
    "wire", "decode_q_wait", "decode", "dispatch_q_wait", "dispatch",
    "device", "sink_q_wait", "sink", "writeback", "serve_once",
    "gateway_request"})
LIFECYCLE_SPANS = frozenset({"rollout_swap"})
SERVING_SPAN_ROOT = os.path.join("analytics_zoo_tpu", "serving")
SPAN_CALL_RE = re.compile(
    r"\.\s*add_span\s*\(\s*(?:\n\s*)?['\"]([^'\"]+)['\"]", re.MULTILINE)


def iter_sources(roots) -> List[str]:
    self_path = os.path.abspath(__file__)
    out = []
    for root in roots:
        if os.path.isfile(root):
            out.append(root)
            continue
        for dirpath, _dirs, files in os.walk(root):
            out.extend(os.path.join(dirpath, f)
                       for f in files if f.endswith(".py")
                       # this linter's own docstrings hold deliberate
                       # bad examples
                       and os.path.abspath(os.path.join(dirpath, f))
                       != self_path)
    return sorted(out)


def find_registrations(path: str) -> List[Tuple[str, str, int]]:
    """(kind, name, line) for every literal registration in one file."""
    with open(path, encoding="utf-8") as fh:
        src = fh.read()
    out = []
    for m in CALL_RE.finditer(src):
        line = src.count("\n", 0, m.start()) + 1
        out.append((m.group(1), m.group(2), line))
    return out


def check(roots=DEFAULT_ROOTS) -> List[str]:
    errors: List[str] = []
    seen: Dict[str, Tuple[str, str, int]] = {}   # name -> (kind, file, ln)
    for path in iter_sources(roots):
        for kind, name, line in find_registrations(path):
            where = f"{path}:{line}"
            if not NAME_RE.match(name):
                errors.append(
                    f"{where}: {kind} {name!r} is not snake_case")
            if kind == "counter" and not name.endswith(COUNTER_SUFFIX):
                errors.append(
                    f"{where}: counter {name!r} must end with '_total'")
            if kind == "histogram" and not name.endswith(HIST_SUFFIXES):
                errors.append(
                    f"{where}: histogram {name!r} must end with a unit "
                    f"suffix ({', '.join(HIST_SUFFIXES)})")
            if kind == "gauge" and name.endswith(COUNTER_SUFFIX):
                errors.append(
                    f"{where}: gauge {name!r} must not end with '_total' "
                    "(that suffix claims a monotonic counter)")
            prev = seen.get(name)
            if prev is not None and prev[0] != kind:
                errors.append(
                    f"{where}: {name!r} registered as {kind} but already "
                    f"a {prev[0]} at {prev[1]}:{prev[2]}")
            else:
                seen.setdefault(name, (kind, path, line))
    # required-coverage pass only when linting the real tree (unit tests
    # lint synthetic snippets in tmp dirs)
    if tuple(roots) == DEFAULT_ROOTS:
        for name, kind in sorted(REQUIRED.items()):
            got = seen.get(name)
            if got is None:
                errors.append(
                    f"required metric {name!r} ({kind}) is not registered "
                    "anywhere in the codebase")
            elif got[0] != kind:
                errors.append(
                    f"required metric {name!r} must be a {kind}, found "
                    f"{got[0]} at {got[1]}:{got[2]}")
        errors.extend(check_docs())
        errors.extend(check_spans())
    return errors


def _call_window(src: str, start: int, limit: int = 4000) -> str:
    """The balanced-paren argument window of the call starting at
    `start` (bounded: lint, not a parser)."""
    i = src.index("(", start)
    depth = 0
    for j in range(i, min(len(src), i + limit)):
        ch = src[j]
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
            if depth == 0:
                return src[i:j + 1]
    return src[i:i + limit]


def check_spans(root: str = SERVING_SPAN_ROOT) -> List[str]:
    """Span-name lint (ISSUE 17): every literal `add_span("name", ...)`
    in the serving package must use the stage vocabulary, and request
    spans must propagate a trace_id/trace_ids — otherwise the span can
    never join a request's merged cross-process timeline."""
    errors: List[str] = []
    vocab = REQUEST_SPANS | LIFECYCLE_SPANS
    for path in iter_sources([root]):
        with open(path, encoding="utf-8") as fh:
            src = fh.read()
        for m in SPAN_CALL_RE.finditer(src):
            name = m.group(1)
            line = src.count("\n", 0, m.start()) + 1
            where = f"{path}:{line}"
            if name not in vocab:
                errors.append(
                    f"{where}: span {name!r} is not in the serving "
                    f"stage vocabulary ({', '.join(sorted(vocab))}) — "
                    "the trace assembler's critical-path columns key on "
                    "these names")
            elif name in REQUEST_SPANS:
                window = _call_window(src, m.start())
                if "trace_id" not in window:   # matches trace_ids too
                    errors.append(
                        f"{where}: request span {name!r} carries no "
                        "trace_id/trace_ids — it can never join a "
                        "request's merged timeline")
    return errors


def check_docs(doc_path: str = OBSERVABILITY_DOC,
               required=None) -> List[str]:
    """Docs-drift pass: every REQUIRED family must be mentioned in the
    observability guide. The match is a plain substring — a table row, a
    prose mention, or a code block all count; what cannot happen is a
    load-bearing family shipping with no documentation at all."""
    required = REQUIRED if required is None else required
    if not os.path.exists(doc_path):
        return [f"{doc_path}: observability guide missing — required "
                "metric families have nowhere to be documented"]
    with open(doc_path, encoding="utf-8") as fh:
        text = fh.read()
    return [f"{doc_path}: required metric {name!r} is not documented "
            "(docs drift — add it to the guide's tables)"
            for name in sorted(required) if name not in text]


def main(argv=None) -> int:
    roots = (argv if argv else None) or list(DEFAULT_ROOTS)
    errors = check(roots)
    for e in errors:
        print(e)
    if errors:
        print(f"{len(errors)} metric-name violation(s)")
        return 1
    n = sum(len(find_registrations(p)) for p in iter_sources(roots))
    print(f"metric names OK ({n} registrations checked)")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
