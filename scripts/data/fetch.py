"""Dataset fetchers (the reference's `scripts/data/*/get_*.sh` role).

Each dataset downloads from its canonical public source into
`<dir>/<name>/` — or, with `--synthetic`, generates a small same-format
stand-in locally (for air-gapped dev rigs and CI: every reader in the
framework can be exercised without network).

    python scripts/data/fetch.py movielens-1m ./data
    python scripts/data/fetch.py news20 ./data --synthetic
    python scripts/data/fetch.py all ./data --synthetic
"""

from __future__ import annotations

import argparse
import os
import sys

URLS = {
    "movielens-1m":
        "https://files.grouplens.org/datasets/movielens/ml-1m.zip",
    "news20":
        "http://qwone.com/~jason/20Newsgroups/20news-18828.tar.gz",
    "glove":
        "https://nlp.stanford.edu/data/glove.6B.zip",
    "nyc-taxi":
        "https://raw.githubusercontent.com/numenta/NAB/master/data/"
        "realKnownCause/nyc_taxi.csv",
}


def _download(url: str, dest: str):
    import urllib.request
    print(f"downloading {url} -> {dest}")
    os.makedirs(os.path.dirname(dest), exist_ok=True)
    urllib.request.urlretrieve(url, dest)
    if dest.endswith(".zip"):
        import zipfile
        with zipfile.ZipFile(dest) as z:
            z.extractall(os.path.dirname(dest))
    elif dest.endswith((".tar.gz", ".tgz")):
        import tarfile
        with tarfile.open(dest) as t:
            # 'data' filter blocks tar-slip path traversal from a
            # tampered archive
            t.extractall(os.path.dirname(dest), filter="data")


# -- synthetic same-format generators ---------------------------------------
def _synth_movielens(out: str, n_users=200, n_items=120, n=5000, seed=0):
    """ml-1m layout: ratings.dat with ``user::item::rating::ts`` rows."""
    import numpy as np
    rs = np.random.RandomState(seed)
    os.makedirs(out, exist_ok=True)
    with open(os.path.join(out, "ratings.dat"), "w") as fh:
        for _ in range(n):
            fh.write(f"{rs.randint(1, n_users)}::"
                     f"{rs.randint(1, n_items)}::"
                     f"{rs.randint(1, 6)}::{978300000 + rs.randint(1e6)}\n")
    with open(os.path.join(out, "movies.dat"), "w",
              encoding="latin-1") as fh:
        for i in range(1, n_items):
            fh.write(f"{i}::Movie {i} (2000)::Drama\n")


def _synth_news20(out: str, n_per_group=20, seed=0):
    """20news layout: ``<group>/<doc-id>`` text files."""
    import numpy as np
    rs = np.random.RandomState(seed)
    words = ["tpu", "mesh", "kernel", "market", "game", "engine",
             "stream", "model", "trade", "score"]
    for g, group in enumerate(("comp.graphics", "rec.sport.hockey",
                               "sci.space")):
        gdir = os.path.join(out, group)
        os.makedirs(gdir, exist_ok=True)
        for i in range(n_per_group):
            body = " ".join(rs.choice(words, 40 + g * 5))
            with open(os.path.join(gdir, str(10000 + i)), "w") as fh:
                fh.write(f"Subject: sample {i}\n\n{body}\n")


def _synth_glove(out: str, dim=50, vocab=200, seed=0):
    """glove.6B layout: ``word v1 v2 ...`` text lines."""
    import numpy as np
    rs = np.random.RandomState(seed)
    os.makedirs(out, exist_ok=True)
    with open(os.path.join(out, f"glove.6B.{dim}d.txt"), "w") as fh:
        for i in range(vocab):
            vec = " ".join(f"{v:.4f}" for v in rs.randn(dim) * 0.3)
            fh.write(f"word{i} {vec}\n")


def _synth_nyc_taxi(out: str, n=2000, seed=0):
    """NAB layout: ``timestamp,value`` csv — strictly increasing
    30-minute intervals with daily seasonality plus a few injected
    anomalies."""
    import datetime

    import numpy as np
    rs = np.random.RandomState(seed)
    os.makedirs(out, exist_ok=True)
    t = np.arange(n)
    base = 15000 + 6000 * np.sin(2 * np.pi * t / 48.0)
    vals = base + 800 * rs.randn(n)
    for idx in rs.choice(n, 5, replace=False):
        vals[idx] *= 2.2
    start = datetime.datetime(2014, 7, 1)
    with open(os.path.join(out, "nyc_taxi.csv"), "w") as fh:
        fh.write("timestamp,value\n")
        for i, v in enumerate(vals):
            ts = start + datetime.timedelta(minutes=30 * i)
            fh.write(f"{ts:%Y-%m-%d %H:%M:%S},{v:.0f}\n")


SYNTH = {"movielens-1m": _synth_movielens, "news20": _synth_news20,
         "glove": _synth_glove, "nyc-taxi": _synth_nyc_taxi}


def fetch(name: str, base_dir: str, synthetic: bool = False):
    out = os.path.join(base_dir, name)
    if synthetic:
        SYNTH[name](out)
        print(f"synthetic {name} written to {out}")
    else:
        url = URLS[name]
        _download(url, os.path.join(out, url.rsplit("/", 1)[-1]))
        print(f"{name} downloaded to {out}")
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("dataset", choices=sorted(URLS) + ["all"])
    p.add_argument("dir", nargs="?", default="./data")
    p.add_argument("--synthetic", action="store_true",
                   help="generate a small same-format local stand-in "
                        "instead of downloading")
    args = p.parse_args(argv)
    names = sorted(URLS) if args.dataset == "all" else [args.dataset]
    for name in names:
        fetch(name, args.dir, synthetic=args.synthetic)
    return 0


if __name__ == "__main__":
    sys.exit(main())
