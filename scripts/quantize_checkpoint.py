#!/usr/bin/env python
"""Offline post-training int8 quantization for training checkpoints
(ISSUE 12) — the shipped-artifact shape of the reference's int8
OpenVINO IR (`OpenVinoInferenceSupportive.scala:34-57`): calibrate
symmetric per-output-channel scales from a checkpoint's weights and
write them as an int8 sidecar beside `model.<version>`, so serving
(`InferenceModel.load_checkpoint(..., quantize="int8")` or a
ClusterServing config with `model.quantize: int8`) loads the
pre-calibrated artifact instead of re-quantizing at every restart.

    python scripts/quantize_checkpoint.py \
        --checkpoint /ckpts/bert --model /models/bert_cls

`--model` is a saved ZooModel directory (its config.json names the
architecture class, like the serving config's model resolution);
`--version` defaults to the newest intact checkpoint. The quality gate
lives in `Estimator.evaluate(..., quantize="int8",
quality_tolerance=...)` — run it on held-out data before blessing the
sidecar for production.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def quantize_checkpoint(checkpoint: str, model_dir: str,
                        version=None) -> dict:
    """Run the pass; returns a summary dict (run_dir, version, sidecar
    path, f32 vs int8 artifact bytes)."""
    from analytics_zoo_tpu.learn.checkpoint import resolve_checkpoint
    from analytics_zoo_tpu.serving.config import _find_model_class
    from analytics_zoo_tpu.serving.quantization import write_int8_sidecar

    run_dir, version = resolve_checkpoint(
        checkpoint, None if version is None else int(version))

    cfg_json = os.path.join(model_dir, "config.json")
    if not os.path.exists(cfg_json):
        raise FileNotFoundError(
            f"{model_dir} is not a saved ZooModel directory "
            "(no config.json); save the architecture with "
            "save_model(...) first")
    with open(cfg_json) as fh:
        blob = json.load(fh)
    cls = _find_model_class(blob["class"])
    inst = cls(**(blob.get("config") or {}))

    sidecar = write_int8_sidecar(run_dir, version, inst)
    f32_bytes = os.path.getsize(
        os.path.join(run_dir, f"model.{version}.npz"))
    int8_bytes = os.path.getsize(sidecar + ".npz")
    return {"run_dir": run_dir, "version": version,
            "sidecar": sidecar + ".npz",
            "f32_bytes": f32_bytes, "int8_bytes": int8_bytes,
            "shrink": round(f32_bytes / max(int8_bytes, 1), 2)}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--checkpoint", required=True,
                    help="checkpoint root (or run dir with --version)")
    ap.add_argument("--version", type=int, default=None,
                    help="checkpoint version (default: newest intact)")
    ap.add_argument("--model", required=True,
                    help="saved ZooModel directory naming the "
                         "architecture (config.json)")
    args = ap.parse_args(argv)
    try:
        out = quantize_checkpoint(args.checkpoint, args.model,
                                  args.version)
    except (FileNotFoundError, ValueError) as e:
        print(str(e), file=sys.stderr)
        return 1
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
