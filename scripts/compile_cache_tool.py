#!/usr/bin/env python
"""Maintenance CLI for a persistent compilation cache directory.

Reuses the cache package's entry/index format (`compile_cache/store.py`
— the directory IS the index; every entry file is self-describing), so
this tool works on any cache dir without the serving process running:

    python scripts/compile_cache_tool.py ls     --dir /var/cache/zoo-cc
    python scripts/compile_cache_tool.py stats  --dir /var/cache/zoo-cc
    python scripts/compile_cache_tool.py prune  --dir ... --max-bytes 512M
    python scripts/compile_cache_tool.py clear  --dir /var/cache/zoo-cc

`ls` prints one line per entry (oldest-touched first — the LRU eviction
order) with the key anatomy from the header: kind, placement, bucket
shape/dtype, jax version. `prune` applies the same LRU policy the
serving process enforces under `compile_cache_max_bytes`.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from analytics_zoo_tpu.compile_cache.store import (  # noqa: E402
    dir_bytes, prune_dir, scan_dir)
from analytics_zoo_tpu.serving.config import _parse_bytes  # noqa: E402


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024
    return f"{n:.1f}GiB"


def _age(ts) -> str:
    if not ts:
        return "?"
    s = max(0, time.time() - float(ts))
    for div, unit in ((86400, "d"), (3600, "h"), (60, "m")):
        if s >= div:
            return f"{s / div:.1f}{unit}"
    return f"{s:.0f}s"


def _entry_line(e) -> str:
    if "corrupt" in e:
        return (f"{e['digest'][:12]}  {_fmt_bytes(e['bytes']):>9}  "
                f"CORRUPT: {e['corrupt']}")
    h = e.get("header", {})
    sig = h.get("signature") or {}
    leaves = sig.get("leaves") or []
    # the batch input is the last leaf (params lead); show every distinct
    # shape compactly
    shapes = ",".join(
        "x".join(map(str, shape)) + f":{dtype}"
        for shape, dtype in leaves[-1:]) or "?"
    return (f"{e['digest'][:12]}  {_fmt_bytes(e['bytes']):>9}  "
            f"used {_age(e['last_used']):>6} ago  "
            f"{h.get('kind', '?'):>7}  {h.get('placement', '?'):>10}  "
            f"in={shapes}  jax={h.get('jax', '?')}")


def cmd_ls(args) -> int:
    entries = scan_dir(args.dir)
    if args.json:
        print(json.dumps(entries, default=str))
        return 0
    if not entries:
        print(f"(no cache entries in {args.dir})")
        return 0
    for e in entries:
        print(_entry_line(e))
    print(f"{len(entries)} entries, {_fmt_bytes(dir_bytes(args.dir))}")
    return 0


def cmd_stats(args) -> int:
    entries = scan_dir(args.dir)
    by_kind = {}
    for e in entries:
        k = e.get("header", {}).get("kind", "corrupt"
                                    if "corrupt" in e else "?")
        by_kind.setdefault(k, [0, 0])
        by_kind[k][0] += 1
        by_kind[k][1] += e["bytes"]
    print(json.dumps({
        "path": os.path.abspath(args.dir),
        "entries": len(entries),
        "bytes": sum(e["bytes"] for e in entries),
        "corrupt": sum(1 for e in entries if "corrupt" in e),
        "by_kind": {k: {"entries": n, "bytes": b}
                    for k, (n, b) in sorted(by_kind.items())},
        "oldest_used": min((e["last_used"] for e in entries),
                           default=None),
        "newest_used": max((e["last_used"] for e in entries),
                           default=None),
    }))
    return 0


def cmd_prune(args) -> int:
    try:
        budget = _parse_bytes(args.max_bytes)
    except ValueError as e:
        raise SystemExit(str(e)) from None
    if budget <= 0:
        raise SystemExit(f"--max-bytes {args.max_bytes!r} must be positive")
    removed, freed = prune_dir(args.dir, budget)
    print(f"pruned {removed} entr{'y' if removed == 1 else 'ies'} "
          f"({_fmt_bytes(freed)}); {_fmt_bytes(dir_bytes(args.dir))} "
          f"remain under the {_fmt_bytes(budget)} budget")
    return 0


def cmd_clear(args) -> int:
    removed, freed = prune_dir(args.dir, -1)
    print(f"cleared {removed} entr{'y' if removed == 1 else 'ies'} "
          f"({_fmt_bytes(freed)})")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="compile-cache-tool", description=__doc__.splitlines()[0])
    sub = p.add_subparsers(dest="cmd", required=True)
    for name, fn, hlp in (("ls", cmd_ls, "list entries, LRU order"),
                          ("stats", cmd_stats, "aggregate stats as JSON"),
                          ("prune", cmd_prune,
                           "evict LRU entries past a byte budget"),
                          ("clear", cmd_clear, "remove every entry")):
        sp = sub.add_parser(name, help=hlp)
        sp.add_argument("--dir", required=True,
                        help="cache directory (compile_cache_dir)")
        if name == "ls":
            sp.add_argument("--json", action="store_true",
                            help="machine-readable index dump")
        if name == "prune":
            sp.add_argument("--max-bytes", required=True,
                            help='byte budget, e.g. 1048576 or "512M"')
        sp.set_defaults(fn=fn)
    args = p.parse_args(argv)
    if not os.path.isdir(args.dir):
        raise SystemExit(f"{args.dir!r} is not a directory")
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
