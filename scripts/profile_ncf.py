"""Per-op device-time attribution for the NCF training step (VERDICT r4 #1).

Runs a warmed Estimator.fit under jax.profiler, parses the xplane proto
(docs/DeveloperGuide/profiling.md recipe), and prints per-op device time
grouped by category plus the wall/device split.

    python scripts/profile_ncf.py [--lazy] [--batch 8192] [--spr 64]
"""

from __future__ import annotations

import argparse
import glob
import os
import sys
import tempfile
import time
from collections import defaultdict

os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if ("JAX_DEFAULT_PRNG_IMPL" not in os.environ
        and jax.default_backend() == "tpu"):
    jax.config.update("jax_default_prng_impl", "rbg")

import numpy as np


def categorize(name: str) -> str:
    n = name.lower()
    if "rng-bit-generator" in n or "rng_bit" in n:
        return "rng"
    if "multiply_add" in n or "adam" in n:
        return "adam-fusion"
    if "scatter" in n:
        return "scatter"
    if "gather" in n:
        return "gather"
    if "convolution" in n or "dot" in n:
        return "matmul"
    if "copy" in n or "slice" in n or "transpose" in n or "reshape" in n:
        return "data-movement"
    if "tpu_custom_call" in n:
        return "pallas"
    if "fusion" in n:
        return "other-fusion"
    if "infeed" in n or "outfeed" in n:
        return "infeed/outfeed"
    return "other"


def parse_xplane(trace_dir: str):
    from tensorflow.tsl.profiler.protobuf import xplane_pb2
    paths = glob.glob(os.path.join(trace_dir, "plugins/profile/*/*.xplane.pb"))
    assert paths, f"no xplane under {trace_dir}"
    xs = xplane_pb2.XSpace()
    with open(paths[0], "rb") as f:
        xs.ParseFromString(f.read())
    per_op = defaultdict(float)
    for plane in xs.planes:
        if "/device:TPU:0" not in plane.name:
            continue
        for line in plane.lines:
            if line.name != "XLA Ops":
                continue
            for e in line.events:
                name = plane.event_metadata[e.metadata_id].name
                if name.startswith("%while"):
                    continue  # outer scan: contains everything
                per_op[name] += e.duration_ps / 1e12
    return dict(per_op)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--lazy", action="store_true")
    ap.add_argument("--batch", type=int, default=8192)
    ap.add_argument("--spr", type=int, default=64)
    ap.add_argument("--n", type=int, default=1 << 20)
    args = ap.parse_args()

    from analytics_zoo_tpu import init_orca_context
    from analytics_zoo_tpu.learn.estimator import Estimator
    from analytics_zoo_tpu.models.recommendation import NeuralCF

    users, items = 138_000, 27_000
    init_orca_context(cluster_mode="local")
    ncf = NeuralCF(user_count=users, item_count=items, class_num=2,
                   mf_embed=64, user_embed=64, item_embed=64,
                   hidden_layers=(128, 64, 32))
    est = Estimator.from_keras(ncf.model, optimizer="adam",
                               loss="sparse_categorical_crossentropy")
    rs = np.random.RandomState(0)
    n = args.n
    x = np.stack([rs.randint(1, users, n), rs.randint(1, items, n)],
                 axis=1).astype(np.int32)
    y = rs.randint(0, 2, n).astype(np.int32)
    fit_kw = dict(epochs=1, batch_size=args.batch, steps_per_run=args.spr,
                  lazy_embeddings=args.lazy)

    est.fit((x, y), **fit_kw)          # warmup
    steps = n // args.batch

    trace_dir = tempfile.mkdtemp(prefix="ncf_prof_")
    jax.profiler.start_trace(trace_dir)
    t0 = time.perf_counter()
    est.fit((x, y), **fit_kw)
    wall = time.perf_counter() - t0
    jax.profiler.stop_trace()

    per_op = parse_xplane(trace_dir)
    total_dev = sum(per_op.values())
    cats = defaultdict(float)
    for name, s in per_op.items():
        cats[categorize(name)] += s

    print(f"\nwall {wall*1e3:.1f} ms  device {total_dev*1e3:.1f} ms  "
          f"host/transfer {max(0.0, wall-total_dev)*1e3:.1f} ms  "
          f"steps {steps}  wall/step {wall/steps*1e3:.3f} ms  "
          f"device/step {total_dev/steps*1e3:.3f} ms")
    print("\nby category (device ms/step):")
    for c, s in sorted(cats.items(), key=lambda kv: -kv[1]):
        print(f"  {c:16s} {s/steps*1e3:8.3f} ms  "
              f"({100*s/total_dev:5.1f}% of device)")
    print("\ntop 20 ops (device ms/step):")
    for name, s in sorted(per_op.items(), key=lambda kv: -kv[1])[:20]:
        print(f"  {s/steps*1e3:8.3f} ms  {name[:110]}")
    print("\ntop 12 data-movement ops (device ms/step):")
    dm = [(n, s) for n, s in per_op.items()
          if categorize(n) == "data-movement"]
    for name, s in sorted(dm, key=lambda kv: -kv[1])[:12]:
        print(f"  {s/steps*1e3:8.3f} ms  {name[:110]}")


if __name__ == "__main__":
    main()
