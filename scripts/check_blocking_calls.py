#!/usr/bin/env python
"""Static lint for unbounded blocking calls (ISSUE 5 satellite; tier-1
via tests/test_fault_tolerance.py).

A fault-tolerant serving engine must never block forever: a wedged
queue peer or a dead socket has to surface as a timeout some layer can
act on (backoff, quarantine, drain). This lint enforces that statically
over `analytics_zoo_tpu/serving/` (and the training input pipeline,
`analytics_zoo_tpu/data/pipeline.py` — its worker pool and reorder
buffer pace training the way the serving stages pace inference, ISSUE
15: an untimed queue/condition wait there is a hung fit):

- `Queue.get()` with no arguments (an indefinite block) is banned —
  use `get(timeout=...)` in a loop, or `get_nowait()`. A no-argument
  `.get()` can only be a queue (dict.get needs a key), so the check is
  precise.
- `.put(...)` without a `timeout=` keyword is banned unless it is
  `put_nowait`. (`device_put`/`_put` helpers do not match the `.put(`
  spelling.)
- `.join()` with no timeout is banned (`"sep".join(...)` always has an
  argument, so only thread/process joins match).
- `.wait()` with no arguments is banned (ISSUE 10: the heartbeat /
  claim-sweep threads must never park forever on an Event or Condition
  a dead peer will never signal — pass `wait(timeout)` in a loop).
- `socket.create_connection(...)` must pass `timeout=`.
- control-loop modules (`serving/fleet.py`, `serving/elastic.py` —
  the autoscaler/heartbeat/admission control paths, ISSUE 11) may not
  call `time.sleep(...)` at all: a sleep is uninterruptible by the
  stop event, so every pause in a control loop must be a timed
  `Event.wait(timeout)` that a shutdown can cut short. A scale-down
  or gateway stop must never wait out someone's nap.

And over the WHOLE `analytics_zoo_tpu/` package:

- bare `except:` is banned everywhere (it swallows KeyboardInterrupt
  and SystemExit — a hung shutdown is a fault-tolerance bug).

A line may opt out with a trailing `# blocking-ok: <reason>` comment;
the reason is mandatory so the waiver documents itself.

    python scripts/check_blocking_calls.py [repo_root]
"""

from __future__ import annotations

import os
import re
import sys
from typing import List, Tuple

SERVING_PKG = os.path.join("analytics_zoo_tpu", "serving")
WHOLE_PKG = "analytics_zoo_tpu"
# modules OUTSIDE serving/ that get the full blocking-call rule set:
# the parallel input pipeline's pool/reorder machinery (ISSUE 15)
EXTRA_STRICT_FILES = (
    os.path.join("analytics_zoo_tpu", "data", "pipeline.py"),
)

ALLOW_RE = re.compile(r"#\s*blocking-ok:\s*\S")
# modules whose loops steer the fleet: no time.sleep, only stop-event
# waits (a sleep delays shutdown/retire by its full duration)
CONTROL_LOOP_FILES = (
    os.path.join(SERVING_PKG, "fleet.py"),
    os.path.join(SERVING_PKG, "elastic.py"),
    # the rollout control plane (ISSUE 14): agent + controller loops
    # pace on stop-event waits only — a sleep would hold a paused
    # engine's intake (or a gateway shutdown) hostage for its duration
    os.path.join(SERVING_PKG, "rollout.py"),
    # the partitioned request plane (ISSUE 16): lease-table polling and
    # gateway leader election pace on stop-event waits only — a sleep
    # here delays a lease renewal past its TTL and hands the partition
    # (or the gateway leadership) to a peer mid-drain
    os.path.join(SERVING_PKG, "partitions.py"),
    # the continuous-batching decode engine (ISSUE 18): the step loop
    # IS the serving latency — a sleep between steps inflates every
    # active sequence's inter-token latency by its full duration; all
    # pacing goes through broker block_ms and stop-event waits
    os.path.join(SERVING_PKG, "decode.py"),
    # the paged KV pool + prefix cache (ISSUE 19): alloc/evict sit on
    # the decode step's critical path under the pool lock — a sleep
    # while holding it would stall every lane's next token
    os.path.join(SERVING_PKG, "paged_kv.py"),
)
SLEEP_RE = re.compile(r"\btime\.sleep\s*\(")
BARE_EXCEPT_RE = re.compile(r"^\s*except\s*:", re.MULTILINE)
GET_NOARG_RE = re.compile(r"\.get\(\s*\)")
JOIN_NOARG_RE = re.compile(r"\.join\(\s*\)")
WAIT_NOARG_RE = re.compile(r"\.wait\(\s*\)")
PUT_RE = re.compile(r"\.put\(")
CONNECT_RE = re.compile(r"\bcreate_connection\s*\(")


def _call_slice(src: str, open_paren: int) -> str:
    """The argument text of the call whose '(' sits at `open_paren`,
    respecting nested parens/brackets (multi-line calls included)."""
    depth = 0
    for i in range(open_paren, len(src)):
        c = src[i]
        if c in "([{":
            depth += 1
        elif c in ")]}":
            depth -= 1
            if depth == 0:
                return src[open_paren + 1:i]
    return src[open_paren + 1:]


def _line_of(src: str, pos: int) -> int:
    return src.count("\n", 0, pos) + 1


def _line_text(src: str, pos: int) -> str:
    start = src.rfind("\n", 0, pos) + 1
    end = src.find("\n", pos)
    return src[start:end if end != -1 else len(src)]


def _allowed(src: str, pos: int) -> bool:
    return bool(ALLOW_RE.search(_line_text(src, pos)))


def check_file(path: str, serving: bool) -> List[str]:
    with open(path, encoding="utf-8") as fh:
        src = fh.read()
    errors = []

    for m in BARE_EXCEPT_RE.finditer(src):
        if not _allowed(src, m.start()):
            errors.append(f"{path}:{_line_of(src, m.start())}: bare "
                          "'except:' (catches KeyboardInterrupt/"
                          "SystemExit; name the exception)")
    if not serving:
        return errors

    if any(path.replace(os.sep, "/").endswith(f.replace(os.sep, "/"))
           for f in CONTROL_LOOP_FILES):
        for m in SLEEP_RE.finditer(src):
            if not _allowed(src, m.start()):
                errors.append(
                    f"{path}:{_line_of(src, m.start())}: time.sleep() "
                    "in a fleet control-loop module delays shutdown/"
                    "retire by its full duration; use a timed "
                    "stop-Event wait(timeout) instead")

    for m in GET_NOARG_RE.finditer(src):
        if not _allowed(src, m.start()):
            errors.append(
                f"{path}:{_line_of(src, m.start())}: '.get()' with no "
                "timeout blocks forever; use get(timeout=...) in a loop "
                "or get_nowait()")
    for m in JOIN_NOARG_RE.finditer(src):
        if not _allowed(src, m.start()):
            errors.append(
                f"{path}:{_line_of(src, m.start())}: '.join()' with no "
                "timeout can hang shutdown; pass join(timeout=...)")
    for m in WAIT_NOARG_RE.finditer(src):
        if not _allowed(src, m.start()):
            errors.append(
                f"{path}:{_line_of(src, m.start())}: '.wait()' with no "
                "timeout parks forever on an event a dead peer may "
                "never signal; pass wait(timeout) in a loop")
    for m in PUT_RE.finditer(src):
        # `put_nowait(` never matches `.put(`; this is a plain `.put(`
        args = _call_slice(src, m.end() - 1)
        if "timeout" not in args and not _allowed(src, m.start()):
            errors.append(
                f"{path}:{_line_of(src, m.start())}: '.put(...)' without "
                "timeout= blocks forever on a full queue; bound it (or "
                "use put_nowait on unbounded queues)")
    for m in CONNECT_RE.finditer(src):
        args = _call_slice(src, m.end() - 1)
        if "timeout" not in args and not _allowed(src, m.start()):
            errors.append(
                f"{path}:{_line_of(src, m.start())}: create_connection "
                "without timeout= hangs on an unreachable host")
    return errors


def iter_py(root: str) -> List[str]:
    out = []
    for dirpath, _dirs, files in os.walk(root):
        out.extend(os.path.join(dirpath, f) for f in files
                   if f.endswith(".py"))
    return sorted(out)


def check(repo_root: str = ".") -> Tuple[List[str], int]:
    serving_root = os.path.join(repo_root, SERVING_PKG)
    pkg_root = os.path.join(repo_root, WHOLE_PKG)
    errors: List[str] = []
    n = 0
    for path in iter_py(pkg_root):
        in_serving = os.path.abspath(path).startswith(
            os.path.abspath(serving_root) + os.sep)
        strict = in_serving or any(
            path.replace(os.sep, "/").endswith(f.replace(os.sep, "/"))
            for f in EXTRA_STRICT_FILES)
        errors.extend(check_file(path, serving=strict))
        n += 1
    return errors, n


def main(argv=None) -> int:
    root = (argv or ["."])[0] if argv else "."
    errors, n = check(root)
    for e in errors:
        print(e)
    if errors:
        print(f"{len(errors)} blocking-call violation(s)")
        return 1
    print(f"blocking calls OK ({n} files checked)")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
