"""PyTorch interop (the reference's `pyzoo/zoo/examples/pytorch/train/` via
JEP + TorchModel; here the torch module converts into native layers whose
weights carry over, then trains as XLA).

    python examples/torch_interop.py
"""

import numpy as np
import torch.nn as nn

from analytics_zoo_tpu import init_orca_context
from analytics_zoo_tpu.learn.estimator import Estimator


def main():
    init_orca_context(cluster_mode="local")
    torch_model = nn.Sequential(
        nn.Linear(10, 32), nn.ReLU(),
        nn.Linear(32, 16), nn.ReLU(),
        nn.Linear(16, 1),
    )
    est = Estimator.from_torch(torch_model, loss="mse", optimizer="adam")

    x = np.random.rand(512, 10).astype(np.float32)
    y = (2 * x.mean(axis=1, keepdims=True)).astype(np.float32)
    est.fit({"x": x, "y": y}, epochs=4, batch_size=64)
    print("eval:", est.evaluate({"x": x, "y": y}, batch_per_thread=128))

    # converted-model predictions start from the torch module's weights
    import torch
    with torch.no_grad():
        ref0 = torch_model(torch.zeros(1, 10)).numpy()
    print("torch f(0) before training:", ref0.ravel()[:1])

    # user-supplied torch loss + optimizer + LR scheduler
    # (`TorchOptim.scala:41-60` interop): converted once to jax/optax,
    # the hot path stays pure XLA
    tmodel2 = nn.Sequential(nn.Linear(10, 16), nn.ReLU(), nn.Linear(16, 1))
    topt = torch.optim.SGD(tmodel2.parameters(), lr=0.05, momentum=0.9)
    tsched = torch.optim.lr_scheduler.StepLR(topt, step_size=2, gamma=0.5)
    est2 = Estimator.from_torch(tmodel2, loss=nn.SmoothL1Loss(),
                                optimizer=topt, scheduler=tsched,
                                steps_per_epoch=512 // 64)
    h = est2.fit({"x": x, "y": y}, epochs=4, batch_size=64)
    print("torch-optim loss curve:", [round(v, 4) for v in h["loss"]])


if __name__ == "__main__":
    main()
