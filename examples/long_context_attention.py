"""Long-context capability demo (beyond the reference, SURVEY.md §5): flash
attention trains at sequence lengths where materialized O(L²) attention
cannot, and ring attention shards the sequence across the device mesh.

On CPU the flash path falls back to exact attention — run on a TPU chip for
the real kernels; ring attention runs anywhere there is a mesh:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
        python examples/long_context_attention.py
"""

import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu import init_orca_context, stop_orca_context
from analytics_zoo_tpu.pallas.flash_attention import flash_attention
from analytics_zoo_tpu.parallel.ring_attention import ring_attention


def main():
    n_dev = jax.device_count()
    seq_shards = min(n_dev, 4)
    ctx = init_orca_context(cluster_mode="local",
                            data=n_dev // seq_shards,
                            sequence=seq_shards)
    print(f"mesh: {ctx.mesh}")

    # flash attention with training gradient (kernel on TPU; exact on CPU)
    B, H, T, D = 2, 4, 1024, 64
    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.randn(B, H, T, D), jnp.float32)

    def loss(q):
        out = flash_attention(q, q, q, dropout_rate=0.1,
                              dropout_seed=jnp.int32(7))
        return jnp.sum(out ** 2)

    g = jax.jit(jax.grad(loss))(q)
    print(f"flash attention T={T}: grad finite ->",
          bool(np.isfinite(np.asarray(g)).all()))

    # ring attention: sequence sharded over the mesh's data axis,
    # K/V blocks rotate via ppermute over ICI
    out = ring_attention(q, q, q, mesh=ctx.mesh)
    print("ring attention output:", np.asarray(out).shape)
    stop_orca_context()


if __name__ == "__main__":
    main()
