"""Question-answer ranking with KNRM (the reference's
`pyzoo/zoo/examples/qaranker/`, WikiQA-style workload) on synthetic pairs
where relevant answers share tokens with the question.

    python examples/qa_ranker.py
"""

import numpy as np

from analytics_zoo_tpu import init_orca_context
from analytics_zoo_tpu.models.textmatching import KNRM


def synthetic_pairs(n=512, vocab=200, q_len=10, a_len=20, seed=0):
    rng = np.random.RandomState(seed)
    q = rng.randint(1, vocab, (n, q_len))
    a = rng.randint(1, vocab, (n, a_len))
    y = rng.randint(0, 2, n).astype(np.float32)
    # positive answers copy question tokens (lexical overlap signal)
    for i in np.where(y == 1)[0]:
        a[i, :q_len] = q[i]
    return np.concatenate([q, a], axis=1).astype(np.int32), y


def main():
    init_orca_context(cluster_mode="local")
    x, y = synthetic_pairs()
    ranker = KNRM(text1_length=10, text2_length=20, vocab_size=200,
                  embed_size=16, target_mode="classification")
    ranker.compile("adam", "binary_crossentropy", ["accuracy"])
    ranker.fit(x, y, batch_size=64, nb_epoch=3)
    metrics = ranker.evaluate(x, y, batch_per_thread=128)
    print("metrics:", metrics)
    # rank 4 candidate answers for one question (3 random, 1 overlapping)
    q = x[:1, :10]
    cands = np.random.RandomState(7).randint(1, 200, (4, 20))
    cands[2, :10] = q[0]
    pairs = np.concatenate([np.repeat(q, 4, axis=0), cands], axis=1)
    scores = np.asarray(ranker.predict(pairs.astype(np.int32),
                                       batch_per_thread=4)).ravel()
    print("candidate scores:", np.round(scores, 3),
          "→ best:", int(np.argmax(scores)))


if __name__ == "__main__":
    main()
