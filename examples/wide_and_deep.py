"""Wide & Deep recommendation (the reference's
`apps/recommendation-wide-n-deep/`, census-style features) on synthetic
user/item data.

    python examples/wide_and_deep.py [--model-type wide_n_deep|wide|deep]
"""

import argparse

import numpy as np

from analytics_zoo_tpu import init_orca_context
from analytics_zoo_tpu.models.recommendation import WideAndDeep


def synthetic(n=2048, seed=0):
    rng = np.random.RandomState(seed)
    # wide: two crossed categorical features, one-hot-ish multi-hot blocks
    gender = rng.randint(0, 2, n)
    age_bucket = rng.randint(0, 8, n)
    occupation = rng.randint(0, 16, n)
    wide = np.zeros((n, 2 + 8), np.float32)
    wide[np.arange(n), gender] = 1.0
    wide[np.arange(n), 2 + age_bucket] = 1.0
    indicator = np.zeros((n, 16), np.float32)
    indicator[np.arange(n), occupation] = 1.0
    embed_ids = np.stack([rng.randint(0, 100, n),
                          rng.randint(0, 50, n)], axis=1).astype(np.int32)
    continuous = rng.rand(n, 2).astype(np.float32)
    label = ((gender + age_bucket + occupation
              + embed_ids[:, 0] // 20) % 5).astype(np.int32)
    return wide, indicator, embed_ids, continuous, label


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model-type", default="wide_n_deep",
                    choices=["wide_n_deep", "wide", "deep"])
    args = ap.parse_args()

    init_orca_context(cluster_mode="local")
    wide, indicator, embed_ids, continuous, label = synthetic()
    wnd = WideAndDeep(class_num=5, model_type=args.model_type,
                      wide_base_dims=(2, 8), wide_cross_dims=(),
                      indicator_dims=(16,), embed_in_dims=(100, 50),
                      embed_out_dims=(8, 8), continuous_cols=("c0", "c1"),
                      hidden_layers=(32, 16))
    wnd.compile("adam", "sparse_categorical_crossentropy", ["accuracy"])
    if args.model_type == "wide":
        x = [wide]
    elif args.model_type == "deep":
        x = [indicator, embed_ids, continuous]
    else:
        x = [wide, indicator, embed_ids, continuous]
    wnd.fit(x, label, batch_size=256, nb_epoch=3)
    print("metrics:", wnd.evaluate(x, label, batch_per_thread=256))


if __name__ == "__main__":
    main()
