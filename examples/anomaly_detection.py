"""Time-series anomaly detection with the LSTM AnomalyDetector (the
reference's `pyzoo/zoo/examples/anomalydetection/`, `apps/anomaly-detection/`).

    python examples/anomaly_detection.py
"""

import numpy as np

from analytics_zoo_tpu import init_orca_context
from analytics_zoo_tpu.models.anomalydetection import (
    AnomalyDetector, detect_anomalies, unroll)


def synthetic_series(n=2000, seed=0):
    rng = np.random.RandomState(seed)
    t = np.arange(n)
    base = np.sin(2 * np.pi * t / 50) + 0.1 * rng.randn(n)
    # inject spikes the detector should flag
    spikes = rng.choice(n, 8, replace=False)
    base[spikes] += rng.choice([-4.0, 4.0], 8)
    return base.astype(np.float32), spikes


def main():
    init_orca_context(cluster_mode="local")
    series, true_spikes = synthetic_series()
    unroll_len = 24
    x, y = unroll(series, unroll_len)
    n_train = int(len(x) * 0.8)
    x_train, y_train = x[:n_train], y[:n_train]
    x_test, y_test = x[n_train:], y[n_train:]

    model = AnomalyDetector(feature_shape=(unroll_len, 1),
                            hidden_layers=(16, 8), dropouts=(0.2, 0.2))
    model.compile("adam", "mse")
    model.fit(x_train, y_train, batch_size=128, nb_epoch=3)

    y_pred = np.asarray(model.predict(x_test, batch_per_thread=128)).ravel()
    anomaly_idx = detect_anomalies(y_test, y_pred, anomaly_size=5)
    print(f"test mse: {np.mean((y_pred - y_test) ** 2):.4f}")
    print(f"flagged anomaly window indices: {sorted(anomaly_idx.tolist())}")


if __name__ == "__main__":
    main()
