"""BERT sequence classification (the reference's TFPark BERTClassifier,
`pyzoo/zoo/tfpark/text/estimator/bert_classifier.py:64`, baseline config 4)
on a tiny randomly-initialized BERT and synthetic token data.

    python examples/bert_classification.py
"""

import numpy as np

from analytics_zoo_tpu import init_orca_context
from analytics_zoo_tpu.models.bert import BERTClassifier


def synthetic_batches(n=64, seq_len=32, vocab=100, classes=2, seed=0):
    rng = np.random.RandomState(seed)
    y = rng.randint(0, classes, n).astype(np.int32)
    ids = rng.randint(5, vocab, (n, seq_len)).astype(np.int32)
    ids[y == 1, :4] = 2  # class-1 sequences start with a marker token
    token_type = np.zeros((n, seq_len), np.int32)
    mask = np.ones((n, seq_len), np.int32)
    return [ids, token_type, mask], y


def main():
    init_orca_context(cluster_mode="local")
    x, y = synthetic_batches()
    clf = BERTClassifier(num_classes=2, vocab=100, hidden_size=32,
                         n_block=2, n_head=2, seq_len=32,
                         intermediate_size=64)
    clf.default_compile(lr=1e-3, total_steps=40)
    clf.fit(x, y, batch_size=16, nb_epoch=5)
    metrics = clf.evaluate(x, y, batch_per_thread=32)
    print("metrics:", metrics)
    logits = np.asarray(clf.predict(x, batch_per_thread=32))
    acc = float((logits.argmax(-1) == y).mean())
    print(f"train accuracy: {acc:.2f}")


if __name__ == "__main__":
    main()
