"""Train from TFRecord shards (the reference's inception path: TFRecord
corpus → `TFDataset`/`TFBytesDataset` → distributed training,
`pyzoo/zoo/tfpark/tf_dataset.py:593,911`;
`pyzoo/zoo/examples/inception/inception.py`).

Generates an ImageNet-style synthetic corpus ("image/encoded" raw bytes +
"image/class/label") across shard files, then streams it through
`TPUDataset.from_tfrecord` into `Estimator.fit` — no materialization of
the whole corpus, shuffle-buffer streaming, static batch shapes.
`--pipeline-workers N` decodes shard files on N threads (the parallel
input pipeline, `data/pipeline.py` — same batches at any N, just
faster); `--prefetch-depth` sizes the trainer's batch prefetch queue.
After the fit it prints the measured input-bound fraction
(`training_input_bound`).

    python examples/tfrecord_training.py --pipeline-workers 4
"""

import argparse
import os
import tempfile

import numpy as np

from analytics_zoo_tpu import init_orca_context
from analytics_zoo_tpu.data import tfrecord as tfr
from analytics_zoo_tpu.data.dataset import TPUDataset
from analytics_zoo_tpu.keras import Sequential
from analytics_zoo_tpu.keras import layers as L
from analytics_zoo_tpu.learn.estimator import Estimator

SIZE = 16  # synthetic "ImageNet" thumbnails
CLASSES = 4


def write_corpus(out_dir: str, n_shards: int = 4, per_shard: int = 64):
    rs = np.random.RandomState(0)
    for s in range(n_shards):
        recs = []
        for _ in range(per_shard):
            label = rs.randint(CLASSES)
            # class-dependent mean so the task is learnable
            img = (rs.rand(SIZE, SIZE, 3) * 64
                   + label * (192 // CLASSES)).astype(np.uint8)
            recs.append(tfr.encode_example({
                "image/encoded": img.tobytes(),
                "image/class/label": np.asarray([label], np.int64),
            }))
        tfr.write_tfrecord(
            os.path.join(out_dir, f"train-{s:05d}-of-{n_shards:05d}"), recs)


def parse_fn(ex):
    img = np.frombuffer(ex["image/encoded"][0], np.uint8)
    img = img.reshape(SIZE, SIZE, 3).astype(np.float32) / 255.0
    return img, ex["image/class/label"].astype(np.int32)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--pipeline-workers", type=int, default=None,
                    help="threads decoding shard files concurrently "
                         "(default: ZOO_PIPELINE_WORKERS / config, "
                         "else 1; any value yields the same batches)")
    ap.add_argument("--prefetch-depth", type=int, default=None,
                    help="trainer prefetch-queue depth (default: "
                         "ZOO_PREFETCH_DEPTH / config, else 2)")
    args = ap.parse_args()
    init_orca_context(cluster_mode="local")
    with tempfile.TemporaryDirectory() as d:
        write_corpus(d)
        ds = TPUDataset.from_tfrecord(
            os.path.join(d, "train-*"), parse_fn,
            batch_size=32, shuffle_buffer=128,
            pipeline_workers=args.pipeline_workers)
        print(f"corpus: {ds.n_samples()} records in 4 shards")

        model = Sequential([
            L.Conv2D(8, 3, 3, input_shape=(SIZE, SIZE, 3),
                     activation="relu", border_mode="same"),
            L.MaxPooling2D((2, 2)),
            L.Flatten(),
            L.Dense(32, activation="relu"),
            L.Dense(CLASSES, activation="softmax"),
        ])
        est = Estimator.from_keras(
            model, optimizer="adam", loss="sparse_categorical_crossentropy")
        fit_kw = {}
        if args.prefetch_depth is not None:
            fit_kw["prefetch_depth"] = args.prefetch_depth
        hist = est.fit(ds, epochs=6, **fit_kw)
        print("loss:", [round(v, 3) for v in hist["loss"]])
        assert hist["loss"][-1] < hist["loss"][0]
        from analytics_zoo_tpu.observability import get_registry
        print("input_bound: %.3f  input_wait p50: %.2f ms" % (
            get_registry().get("training_input_bound").value(),
            get_registry().get("training_input_wait_ms").percentile(0.5)))
        print("TFRecord streaming training OK")


if __name__ == "__main__":
    main()
