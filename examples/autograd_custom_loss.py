"""Autograd Variable DSL + custom loss (the reference's
`pyzoo/zoo/examples/autograd/custom.py` and `customloss.py`): build a
Lambda-style model and train it with a mean-absolute-error expressed in the
Variable math DSL.

    python examples/autograd_custom_loss.py
"""

import numpy as np

from analytics_zoo_tpu import init_orca_context
from analytics_zoo_tpu.keras import Sequential
from analytics_zoo_tpu.keras import layers as L
from analytics_zoo_tpu.ops import autograd as A


def add_one_one(inputs):
    return inputs + 1.0


def main():
    init_orca_context(cluster_mode="local")
    x = np.random.rand(256, 4).astype(np.float32)
    y = (x.sum(axis=1, keepdims=True) + 1.0).astype(np.float32)

    model = Sequential([
        L.Dense(8, input_shape=(4,), activation="relu"),
        L.Dense(1),
    ])
    # mean-absolute-error written in the Variable DSL
    y_true = A.Variable(input_shape=(1,))
    y_pred = A.Variable(input_shape=(1,))
    mae = A.CustomLoss(A.mean(A.abs(y_true - y_pred), axis=1),
                       y_true, y_pred)
    model.compile("adam", mae)
    hist = model.fit(x, y, batch_size=64, nb_epoch=8)
    print("final custom-loss value:", round(hist["loss"][-1], 4))

    # Lambda layer from a plain function (reference's `Lambda` path)
    lam = Sequential([A.Lambda(add_one_one, input_shape=(4,))])
    out = np.asarray(lam.predict(x[:4], batch_per_thread=4))
    np.testing.assert_allclose(out, x[:4] + 1.0, rtol=1e-6)
    print("Lambda(add_one) OK")

    # Parameter: trainable standalone variables in a Variable expression
    # (reference `autograd.py:462`): learn y = w.x + b directly.
    import optax
    from analytics_zoo_tpu.keras import Model
    inp = A.Variable(input_shape=(4,))
    w = A.Parameter((4, 1), name="w")
    b = A.Parameter((1,), name="b")
    lin = Model(inp, A.mm(inp, w) + b)
    lin.compile(optax.adam(0.05), "mse")
    lin.fit(x, y, batch_size=64, nb_epoch=40, distributed=False)
    print("learned w:", np.asarray(w.get_weight(lin.params)).ravel().round(2),
          "b:", np.asarray(b.get_weight(lin.params)).round(2),
          "(target w=1,1,1,1  b=1)")


if __name__ == "__main__":
    main()
