"""Transfer learning by graph surgery (the reference's
`pyzoo/zoo/examples/nnframes/transfer/` + `Net.scala` newGraph/freeze):
train a base model, cut it at an intermediate layer, freeze the trunk, and
fine-tune a new head on a different task.

    python examples/transfer_learning.py
"""

import numpy as np

from analytics_zoo_tpu import init_orca_context
from analytics_zoo_tpu import net as znet
from analytics_zoo_tpu.keras import Input, Model
from analytics_zoo_tpu.keras import layers as L


def main():
    init_orca_context(cluster_mode="local")
    # base task: 3-class problem
    inp = Input(shape=(8,))
    h1 = L.Dense(16, activation="relu", name="feat1")(inp)
    h2 = L.Dense(12, activation="relu", name="feat2")(h1)
    out = L.Dense(3, name="head")(h2)
    base = Model(inp, out)
    base.compile("adam", "sparse_categorical_crossentropy")
    x = np.random.rand(256, 8).astype(np.float32)
    y = (x.sum(axis=1) * 2).astype(np.int32) % 3
    base.fit(x, y, batch_size=64, nb_epoch=2)

    # cut at feat2 → feature extractor carrying trained weights
    trunk = znet.new_graph(base, ["feat2"])
    feats = np.asarray(trunk.predict(x[:4], batch_per_thread=4))
    print("trunk features:", feats.shape)

    # new binary head grafted onto the trunk output node, trunk weights
    # carried over and frozen; only new_head trains
    new_out = L.Dense(2, name="new_head")(h2)
    combined = Model(inp, new_out)
    combined.ensure_built(x[:1])
    for name in ("feat1", "feat2"):
        combined.params[name] = base.params[name]
    tuned = znet.freeze(combined, ["feat1", "feat2"])
    tuned.compile("adam", "sparse_categorical_crossentropy", ["accuracy"])
    y2 = (x[:, 0] > 0.5).astype(np.int32)
    tuned.fit(x, y2, batch_size=64, nb_epoch=3)
    print("fine-tune metrics:", tuned.evaluate(x, y2, batch_per_thread=128))
    assert set(tuned.params) == {"new_head"}


if __name__ == "__main__":
    main()
