"""Streaming text classification — the reference's Spark-Streaming example
(`pyzoo/zoo/examples/streaming/textclassification/
streaming_text_classification.py:1`: a socket text stream à la `nc`,
micro-batched, classified by a TextClassifier, predictions printed)
re-hosted on the framework's own streaming runtime: a plain TCP socket
source feeding micro-batch windows into the jitted predict path. No
Spark — the micro-batch loop is a thread draining a socket, which is all
`socketTextStream` + `foreachRDD` amounted to.

    python examples/streaming_text_classification.py
"""

import socket
import threading
import time

import numpy as np

from analytics_zoo_tpu import init_orca_context
from analytics_zoo_tpu.models.textclassification import TextClassifier

VOCAB, SEQ_LEN, CLASSES = 400, 32, 4
BATCH_WINDOW_S = 0.15


def synthetic_line(rng, cls):
    """Class-banded token text (the example's stand-in for news20 lines:
    'label<tab>tokens')."""
    band = VOCAB // CLASSES
    toks = rng.randint(cls * band, (cls + 1) * band, SEQ_LEN)
    return f"{cls}\t" + " ".join(map(str, toks))


def producer(host, port, n_lines, seed=1):
    """The `nc`/image_path_writer role: connect and stream lines."""
    rng = np.random.RandomState(seed)
    sock = socket.create_connection((host, port))
    for i in range(n_lines):
        line = synthetic_line(rng, int(rng.randint(CLASSES)))
        sock.sendall((line + "\n").encode())
        time.sleep(0.005)           # a trickle, like a live feed
    sock.close()


def encode(lines):
    """text → fixed-length token ids (the reference pads/truncates to
    sequence_length before TextClassifier.predict)."""
    xs, ys = [], []
    for ln in lines:
        label, _, body = ln.partition("\t")
        toks = [int(t) for t in body.split()][:SEQ_LEN]
        toks += [0] * (SEQ_LEN - len(toks))
        xs.append(toks)
        ys.append(int(label))
    return np.asarray(xs, np.int32), np.asarray(ys, np.int32)


def main():
    init_orca_context(cluster_mode="local")

    # train the classifier the stream will use (news20 stand-in corpus)
    rng = np.random.RandomState(0)
    lines = [synthetic_line(rng, int(rng.randint(CLASSES)))
             for _ in range(768)]
    x, y = encode(lines)
    clf = TextClassifier(class_num=CLASSES, vocab_size=VOCAB,
                         embedding_dim=32, sequence_length=SEQ_LEN,
                         encoder="cnn", encoder_output_dim=64)
    clf.compile("adam", "sparse_categorical_crossentropy", ["accuracy"])
    clf.fit(x, y, batch_size=128, nb_epoch=5)

    # socket text stream: listener + producer thread + micro-batch loop
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    host, port = srv.getsockname()
    n_lines = 64
    threading.Thread(target=producer, args=(host, port, n_lines),
                     daemon=True).start()
    conn, _ = srv.accept()
    conn.settimeout(5.0)

    buf = b""
    done = False
    seen = correct = batches = 0
    while not done:
        window_end = time.monotonic() + BATCH_WINDOW_S
        while time.monotonic() < window_end:
            try:
                chunk = conn.recv(4096)
            except socket.timeout:
                chunk = b""
            if not chunk:
                done = True
                break
            buf += chunk
        *complete, buf = buf.split(b"\n")
        lines = [c.decode() for c in complete if c]
        if not lines:
            continue
        xb, yb = encode(lines)
        pred = np.argmax(np.asarray(clf.predict(xb, batch_per_thread=64)),
                         axis=-1)
        batches += 1
        seen += len(lines)
        correct += int((pred == yb).sum())
        print(f"micro-batch {batches}: {len(lines)} lines, "
              f"running accuracy {correct / seen:.2f}")
    conn.close()
    srv.close()

    print(f"stream done: {seen} lines in {batches} micro-batches, "
          f"accuracy {correct / seen:.2f}")
    assert seen == n_lines, f"dropped lines: {seen}/{n_lines}"
    assert correct / seen > 0.5
    print("OK")


if __name__ == "__main__":
    main()
