"""Session-based recommendation (the reference's SessionRecommender,
`models/recommendation/session_recommender.py`) on synthetic click
sessions with sequential structure.

    python examples/session_recommender.py
"""

import numpy as np

from analytics_zoo_tpu import init_orca_context
from analytics_zoo_tpu.models.recommendation import SessionRecommender


def synthetic_sessions(n=1024, items=50, sess_len=6, seed=0):
    """Next item = (last item + 1) mod items, with noise — learnable
    sequential pattern. Item ids are 1-based (0 = padding)."""
    rng = np.random.RandomState(seed)
    start = rng.randint(1, items + 1, n)
    sessions = np.stack([(start + i - 1) % items + 1
                         for i in range(sess_len)], axis=1)
    label = (sessions[:, -1]) % items + 1
    flip = rng.rand(n) < 0.1
    label[flip] = rng.randint(1, items + 1, flip.sum())
    return sessions.astype(np.int32), (label - 1).astype(np.int32)


def main():
    init_orca_context(cluster_mode="local")
    x, y = synthetic_sessions()
    rec = SessionRecommender(item_count=50, item_embed=16,
                             rnn_hidden_layers=(24, 12), session_length=6)
    rec.compile("adam", "sparse_categorical_crossentropy", ["accuracy"])
    rec.fit(x, y, batch_size=128, nb_epoch=6)
    metrics = rec.evaluate(x, y, batch_per_thread=256)
    print("metrics:", metrics)
    probs = np.asarray(rec.predict(x[:4], batch_per_thread=4))
    top3 = np.argsort(-probs, axis=1)[:, :3] + 1
    for sess, items in zip(x[:4], top3):
        print(f"session {sess.tolist()} → top-3 items {items.tolist()}")


if __name__ == "__main__":
    main()
