"""NeuralCF on synthetic MovieLens-style data (the reference's
recommendation-ncf app, `apps/recommendation-ncf/`, baseline config 1).

    python examples/recommendation_ncf.py
"""

import numpy as np

from analytics_zoo_tpu import init_orca_context
from analytics_zoo_tpu.models.recommendation import NeuralCF, UserItemFeature


def synthetic_ratings(n=4096, users=200, items=100, seed=0):
    rng = np.random.RandomState(seed)
    u = rng.randint(1, users + 1, n)
    i = rng.randint(1, items + 1, n)
    # implicit preference structure so there is signal to learn
    label = ((u * 7 + i * 3) % 5 + 1).astype(np.int32)
    return np.stack([u, i], axis=1).astype(np.int32), label


def main():
    init_orca_context(cluster_mode="local")
    x, y = synthetic_ratings()
    ncf = NeuralCF(user_count=200, item_count=100, class_num=5,
                   hidden_layers=(20, 10), include_mf=True)
    ncf.compile("adam", "sparse_categorical_crossentropy", ["accuracy"])
    history = ncf.fit(x, y - 1, batch_size=256, nb_epoch=4)
    print("final loss:", history["loss"][-1])
    metrics = ncf.evaluate(x, y - 1, batch_per_thread=256)
    print("metrics:", metrics)
    candidates = [UserItemFeature(int(u), int(i))
                  for u in np.unique(x[:, 0])[:3]
                  for i in range(1, 101)]
    recs = ncf.recommend_for_user(candidates, max_items=4)
    for user, items in list(recs.items())[:3]:
        print(f"user {user}: {items}")


if __name__ == "__main__":
    main()
