"""Dogs-vs-cats-style fine-tune through the NNFrames DataFrame API (the
reference's `apps/dogs-vs-cats/`, `pyzoo/zoo/examples/nnframes/finetune/`).
Generates a tiny two-class image folder, reads it with NNImageReader,
fine-tunes a small CNN with NNClassifier, and scores with the fitted
NNClassifierModel's `transform`.

    python examples/image_finetune_nnframes.py
"""

import os
import tempfile

import numpy as np

from analytics_zoo_tpu import init_orca_context
from analytics_zoo_tpu.keras import Sequential
from analytics_zoo_tpu.keras import layers as L
from analytics_zoo_tpu.nnframes import NNClassifier, NNImageReader


def write_synthetic_images(root, per_class=12, size=32):
    """Class 0: dark images with a bright square; class 1: bright with a
    dark square — separable by a tiny CNN in a few epochs."""
    from PIL import Image
    rng = np.random.RandomState(0)
    for cls in (0, 1):
        d = os.path.join(root, f"class{cls}")
        os.makedirs(d, exist_ok=True)
        for i in range(per_class):
            base = 40 if cls == 0 else 200
            img = np.clip(base + 20 * rng.randn(size, size, 3), 0, 255)
            r, c = rng.randint(4, size - 12, 2)
            img[r:r + 8, c:c + 8] = 255 - base
            Image.fromarray(img.astype(np.uint8)).save(
                os.path.join(d, f"img{i}.png"))


def main():
    init_orca_context(cluster_mode="local")
    with tempfile.TemporaryDirectory() as root:
        write_synthetic_images(root)
        df = NNImageReader.read_images(root, with_label=True, resize=32)
        df["image"] = df["image"].map(lambda im: im / 255.0 - 0.5)

        model = Sequential([
            L.Convolution2D(8, 3, 3, input_shape=(32, 32, 3),
                            border_mode="same", activation="relu"),
            L.MaxPooling2D(),
            L.Convolution2D(16, 3, 3, border_mode="same",
                            activation="relu"),
            L.GlobalAveragePooling2D(),
            # string losses are probability-space (Keras contract) — the
            # classifier head must end in softmax
            L.Dense(2, activation="softmax"),
        ])
        clf = (NNClassifier(model)
               .set_features_col("image").set_label_col("label")
               .set_batch_size(8).set_max_epoch(8)
               .set_learning_rate(1e-3))
        fitted = clf.fit(df)
        scored = fitted.transform(df)
        acc = float((scored["prediction"] == df["label"]).mean())
        print(f"train accuracy: {acc:.2f}")
        assert acc > 0.7


if __name__ == "__main__":
    main()
