"""Generative serving end-to-end in one process (ISSUE 18): a tiny
causal LM behind the continuous-batching decode engine — pooled KV
slots, pre-compiled prefill/step executables, streamed tokens.

The flow mirrors what `cluster-serving-cli start` does with a
`params.generative` config: load the generative triple into an
InferenceModel, pre-compile every (prompt bucket, kv bucket) program
with `warmup_generative`, start `DecodeServing` on the broker, then
drive it through the standard client — one non-streaming request and
one token-streamed request — and print TTFT / inter-token latency.

    python examples/generative_serving.py
"""

import time

import numpy as np

from analytics_zoo_tpu import init_orca_context
from analytics_zoo_tpu.models.generative import TinyDecoder
from analytics_zoo_tpu.serving.broker import MemoryBroker
from analytics_zoo_tpu.serving.client import InputQueue, OutputQueue
from analytics_zoo_tpu.serving.decode import DecodeServing
from analytics_zoo_tpu.serving.inference_model import InferenceModel

SLOTS, MAX_KV = 4, 64
KV_BUCKETS = [16, 32, 64]
PROMPT_BUCKETS = [8, 16]


def main():
    init_orca_context(cluster_mode="local")
    decoder = TinyDecoder(vocab=64, n_layers=2, n_heads=2, head_dim=8,
                          max_len=MAX_KV)
    model = InferenceModel(placement="replicated", num_replicas=1)
    model.load_generative(decoder.prefill_fn, decoder.step_fn,
                          decoder.init_params(seed=0))
    # every decode-path program compiles HERE; the request path below
    # runs 0 XLA compiles
    model.warmup_generative(decoder.init_kv, slots=SLOTS,
                            max_kv_len=MAX_KV,
                            prompt_buckets=PROMPT_BUCKETS,
                            kv_buckets=KV_BUCKETS)
    print("warmed:", sorted(model.warmup_report))

    broker = MemoryBroker()
    serving = DecodeServing(model, decoder.init_kv, broker=broker,
                            slots=SLOTS, max_kv_len=MAX_KV,
                            kv_buckets=KV_BUCKETS,
                            prompt_buckets=PROMPT_BUCKETS,
                            max_new_default=12).start()
    inq = InputQueue(broker)
    outq = OutputQueue(broker)

    # non-streaming: enqueue, poll the exact uri, get all ids at once
    uri = inq.enqueue(t=np.array([7, 3, 11, 5], np.int32), max_new=8)
    tokens = None
    deadline = time.monotonic() + 30
    while tokens is None and time.monotonic() < deadline:
        tokens = outq.query(uri, delete=True)
        time.sleep(0.005)
    print("batch result:", list(tokens))

    # streaming: tokens arrive one row at a time as they are generated
    uri = inq.enqueue(t=np.array([2, 9, 4], np.int32), max_new=10,
                      stream=1)
    times, ids = [], []
    for event in outq.stream_tokens(uri, timeout_s=30):
        if event.get("done"):
            summary = event["gen"]
            break
        ids.append(event["t"])
        times.append(event["ms"])
    itl = np.diff(times) if len(times) > 1 else np.array([0.0])
    print("streamed result:", ids, f"finish={summary['finish']}")
    print(f"ttft {summary['ttft_ms']:.2f} ms, "
          f"itl mean {itl.mean():.2f} ms / max {itl.max():.2f} ms")

    serving.stop()
    assert len(ids) == summary["n"] == 10
    print("generative serving example done")


if __name__ == "__main__":
    main()
