"""AutoML time-series pipeline search (the reference's
`pyzoo/zoo/examples/automl/`, `zouwu/autots`): AutoTSTrainer searches
feature/model configs, returns the best TSPipeline; save/load round-trip.

    python examples/automl_time_series.py
"""

import os
import tempfile

import numpy as np
import pandas as pd

from analytics_zoo_tpu import init_orca_context
from analytics_zoo_tpu.automl.recipe import LSTMGridRandomRecipe
from analytics_zoo_tpu.zouwu.autots import AutoTSTrainer, TSPipeline


def synthetic_df(n=400):
    dt = pd.date_range("2024-01-01", periods=n, freq="h")
    value = (np.sin(2 * np.pi * np.arange(n) / 24)
             + 0.05 * np.random.RandomState(0).randn(n))
    return pd.DataFrame({"datetime": dt, "value": value.astype(np.float32)})


def main():
    init_orca_context(cluster_mode="local")
    df = synthetic_df()
    n_train = int(len(df) * 0.8)
    train_df, val_df = df.iloc[:n_train], df.iloc[n_train:]

    trainer = AutoTSTrainer(dt_col="datetime", target_col="value")
    pipeline = trainer.fit(train_df, validation_df=val_df,
                           recipe=LSTMGridRandomRecipe(num_rand_samples=1))
    metrics = pipeline.evaluate(val_df, metrics=["mse", "mae"])
    print("best config:", {k: v for k, v in pipeline.config.items()
                           if k in ("model", "lstm_1_units", "past_seq_len")})
    print("validation:", metrics)

    with tempfile.TemporaryDirectory() as d:
        path = pipeline.save(os.path.join(d, "tsppl"))
        reloaded = TSPipeline.load(path)
        m2 = reloaded.evaluate(val_df, metrics=["mse"])
        print("reloaded validation mse:", m2)


if __name__ == "__main__":
    main()
