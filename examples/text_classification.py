"""Text classification with the TextClassifier zoo model (the reference's
`pyzoo/zoo/examples/textclassification/`, news20 workload) on synthetic
token sequences with class-correlated vocabulary.

    python examples/text_classification.py [--encoder cnn|lstm|gru]
"""

import argparse

import numpy as np

from analytics_zoo_tpu import init_orca_context
from analytics_zoo_tpu.models.textclassification import TextClassifier


def synthetic_corpus(n=1024, vocab=500, seq_len=64, classes=4, seed=0):
    """Each class draws tokens from its own slice of the vocab."""
    rng = np.random.RandomState(seed)
    y = rng.randint(0, classes, n)
    band = vocab // classes
    x = np.zeros((n, seq_len), np.int32)
    for i in range(n):
        lo = y[i] * band
        x[i] = rng.randint(lo, lo + band, seq_len)
    return x, y.astype(np.int32)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--encoder", default="cnn", choices=["cnn", "lstm", "gru"])
    ap.add_argument("--epochs", type=int, default=6)
    args = ap.parse_args()

    init_orca_context(cluster_mode="local")
    x, y = synthetic_corpus()
    clf = TextClassifier(class_num=4, vocab_size=500, embedding_dim=32,
                         sequence_length=64, encoder=args.encoder,
                         encoder_output_dim=64)
    # "accuracy" resolves by loss type to sparse_categorical_accuracy
    # (the reference's loss-aware metric dispatch, KerasUtils.scala:218-227)
    clf.compile("adam", "sparse_categorical_crossentropy", ["accuracy"])
    clf.fit(x, y, batch_size=128, nb_epoch=args.epochs)
    metrics = clf.evaluate(x, y, batch_per_thread=256)
    print("train-set metrics:", metrics)
    assert metrics["sparse_categorical_accuracy"] > 0.5, \
        "should beat chance easily"


if __name__ == "__main__":
    main()
