"""Cluster Serving end-to-end in one process (the reference's
`pyzoo/zoo/examples/serving/`, `zoo/.../serving/`): a jit-batched
InferenceModel behind a stream broker, driven by the InputQueue/OutputQueue
client protocol.

    python examples/cluster_serving.py
"""

import numpy as np

from analytics_zoo_tpu import init_orca_context
from analytics_zoo_tpu.keras import Sequential
from analytics_zoo_tpu.keras import layers as L
from analytics_zoo_tpu.serving.broker import MemoryBroker
from analytics_zoo_tpu.serving.client import InputQueue, OutputQueue
from analytics_zoo_tpu.serving.inference_model import InferenceModel
from analytics_zoo_tpu.serving.server import ClusterServing


def main():
    init_orca_context(cluster_mode="local")
    model = Sequential([
        L.Dense(16, input_shape=(8,), activation="relu"),
        L.Dense(3, activation="softmax"),
    ])
    model.ensure_built(np.zeros((1, 8), np.float32))
    infer = InferenceModel(concurrent_num=2).load_keras(model)

    broker = MemoryBroker()
    serving = ClusterServing(infer, broker=broker, batch_size=8)

    inq = InputQueue(broker)
    outq = OutputQueue(broker)
    uris = [inq.enqueue(data=np.random.rand(8).astype(np.float32))
            for _ in range(20)]

    served = 0
    while served < 20:
        served += serving.serve_once()

    results = [outq.query(u) for u in uris]
    probs = np.stack(results)
    print(f"served {served} records; prob rows sum to "
          f"{np.round(probs.sum(axis=1)[:5], 3)}")
    print("serving metrics:", {k: round(v, 4) if isinstance(v, float) else v
                               for k, v in serving.metrics().items()})


if __name__ == "__main__":
    main()
