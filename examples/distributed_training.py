"""Distributed data/tensor-parallel training through the Orca-style
Estimator (the reference's `pyzoo/zoo/examples/orca/learn/`; the five
Spark/Ray gradient transports collapse into GSPMD sharding over the device
mesh here).

Run on any device count — a TPU pod slice, one chip, or a virtual CPU mesh:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
        python examples/distributed_training.py
"""

import jax
import numpy as np

from analytics_zoo_tpu import init_orca_context, stop_orca_context
from analytics_zoo_tpu.keras import Sequential
from analytics_zoo_tpu.keras import layers as L
from analytics_zoo_tpu.learn.estimator import Estimator


def main():
    n_dev = jax.device_count()
    # all devices on the data axis; switch `data=`/`tensor=` to re-shard
    ctx = init_orca_context(cluster_mode="local", data=n_dev)
    print(f"mesh: {ctx.mesh}")

    model = Sequential([
        L.Dense(64, input_shape=(16,), activation="relu"),
        L.Dense(64, activation="relu"),
        L.Dense(1),
    ])
    model.compile("adam", "mse")
    est = Estimator.from_keras(model)

    x = np.random.rand(1024, 16).astype(np.float32)
    y = x.sum(axis=1, keepdims=True).astype(np.float32)
    est.fit({"x": x, "y": y}, epochs=3, batch_size=16 * n_dev)
    mse = est.evaluate({"x": x, "y": y}, batch_per_thread=64)
    print("eval:", mse)
    stop_orca_context()

    # GSPMD-sharded fit (ISSUE 7): params + optimizer state shard over
    # the fsdp axis with the same rule table serving's sharded placement
    # uses — per-device state ≈ 1/fsdp, batch still splits over every
    # device (docs/ProgrammingGuide/distributed-training.md)
    if n_dev > 1:
        ctx = init_orca_context(cluster_mode="local", data=1, fsdp=n_dev)
        print(f"sharded-fit mesh: {ctx.mesh}")
        model = Sequential([
            L.Dense(64, input_shape=(16,), activation="relu"),
            L.Dense(64, activation="relu"),
            L.Dense(1),
        ])
        model.compile("adam", "mse")
        est = Estimator.from_keras(model)
        est.fit({"x": x, "y": y}, epochs=3, batch_size=16 * n_dev,
                sharding_rules=True)
        leaf = jax.tree_util.tree_leaves(model.params)[0]
        print("param sharding after sharded fit:", leaf.sharding.spec)
        stop_orca_context()


if __name__ == "__main__":
    main()
