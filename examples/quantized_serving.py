"""Int8 quantized serving + pretrained-artifact interop — the round-5
surfaces in one walkthrough (reference: the OpenVINO int8 pipeline,
`zoo/examples/vnni/`, and `ImageClassifier.loadModel` of published
artifacts).

1. Write a LeNet "pretrained artifact" in real caffemodel wire format.
2. Load it through the zoo entry point
   (`load_image_classifier(..., weights_path="caffe:...")`).
3. Serve it f32 and int8 through InferenceModel; compare predictions.

    python examples/quantized_serving.py
"""

import os
import tempfile

import numpy as np

from analytics_zoo_tpu import init_orca_context
from analytics_zoo_tpu.models.classification_zoo import (
    load_image_classifier)
from analytics_zoo_tpu.serving.inference_model import InferenceModel


def write_lenet_caffemodel(dirname: str):
    """A pretrained-style artifact: deploy prototxt + binary caffemodel
    (the test fixtures' generator, reused as a stand-in for a download)."""
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tests"))
    from pathlib import Path

    from test_pretrained_interop import _lenet_weights, _write_caffemodel
    return _write_caffemodel(Path(dirname), _lenet_weights(seed=42))


def main():
    init_orca_context(cluster_mode="local")
    with tempfile.TemporaryDirectory() as d:
        def_p, model_p = write_lenet_caffemodel(d)
        clf = load_image_classifier(
            "lenet-mnist", weights_path=f"caffe:{def_p},{model_p}")
        print(f"loaded pretrained artifact through the zoo: {clf.name}")

        rs = np.random.RandomState(0)
        digits = [rs.randint(0, 255, (28, 28)).astype(np.float32)
                  for _ in range(64)]
        batch = clf.preprocess(digits)

        im_f32 = InferenceModel(concurrent_num=2).load_keras(
            clf.classifier)
        im_int8 = InferenceModel(concurrent_num=2).load_keras(
            clf.classifier, quantize="int8")

        p32 = np.asarray(im_f32.predict(batch))
        p8 = np.asarray(im_int8.predict(batch))
        agree = float((p32.argmax(-1) == p8.argmax(-1)).mean())
        drift = float(np.abs(p32 - p8).max())
        print(f"f32 vs int8: top-1 agreement {agree:.3f}, "
              f"max prob drift {drift:.4f}")
        assert agree >= 0.95, "int8 drifted too far from f32"
        top = clf.predict_top_n(digits[:2], top_n=3)
        print(f"top-3 for the first image: {top[0]}")
    print("quantized serving example OK")


if __name__ == "__main__":
    main()
