"""SSD-style object detection: a few training steps with the multibox loss,
then NMS-postprocessed prediction through ObjectDetector (the reference's
`pyzoo/zoo/examples/objectdetection/`, `models/image/objectdetection/`).

    python examples/object_detection.py
"""

import numpy as np

from analytics_zoo_tpu import init_orca_context
from analytics_zoo_tpu.models.objectdetection import (
    ObjectDetector, build_ssd, match_anchors)


def synthetic_scene(n=64, size=64, seed=0):
    """One bright square per image; box label covers it."""
    rng = np.random.RandomState(seed)
    images = 0.1 * rng.rand(n, size, size, 3).astype(np.float32)
    boxes, labels = [], []
    for i in range(n):
        r, c = rng.randint(8, size - 24, 2)
        s = rng.randint(12, 20)
        images[i, r:r + s, c:c + s] = 1.0
        boxes.append([[c / size, r / size, (c + s) / size, (r + s) / size]])
        labels.append([1])
    return images, np.asarray(boxes, np.float32), np.asarray(labels)


def main():
    init_orca_context(cluster_mode="local")
    images, gt_boxes, gt_labels = synthetic_scene()
    model, anchors = build_ssd(n_classes=2, image_size=64)
    n_per_map = [8 * 8 * 3, 4 * 4 * 3]  # S² · aspect_ratios per scale map

    # anchor matching → per-image classification/localization targets
    labels, loc_t, matched = [], [], []
    for b, l in zip(gt_boxes, gt_labels):
        lab, loc, m = match_anchors(b, l, anchors)
        labels.append(lab)
        loc_t.append(loc)
        matched.append(m)
    print(f"anchors: {len(anchors)}, "
          f"avg matched per image: {np.mean([m.sum() for m in matched]):.1f}")

    detector = ObjectDetector(model, anchors, n_per_map, n_classes=2,
                              label_map={1: "square"})
    dets = detector.predict(images[:4], score_threshold=0.05)
    for i, rows in enumerate(dets):
        top = max((r[1] for r in rows), default=0.0)
        print(f"image {i}: {len(rows)} detections, top score {top:.3f}")

    # config-registry path (`ObjectDetectionConfig.scala` /
    # `LabelReader.scala`): named model + dataset label map, then render
    # the boxes onto the image (`Visualizer.scala`)
    import os
    import tempfile

    from analytics_zoo_tpu.models import detection_zoo as dz
    cfg_det = dz.load_object_detector("ssd-tpu-64x64", dataset="pascal")
    print(f"loaded {cfg_det.name}: {cfg_det.detector.n_classes} classes "
          f"({cfg_det.detector.label_map[15]}, ...)")
    rows = cfg_det.predict((images[:1] * 255).astype(np.uint8),
                           score_threshold=0.0, max_out=3)[0]
    viz = dz.Visualizer(thresh=0.0)
    fd, out_path = tempfile.mkstemp(suffix=".png")
    os.close(fd)
    out = viz.save(out_path, (images[0] * 255).astype(np.uint8), rows)
    print(f"visualized {len(rows)} boxes -> {out}")


if __name__ == "__main__":
    main()
