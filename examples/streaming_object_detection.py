"""Streaming object detection — the reference's Spark-Streaming pair
(`pyzoo/zoo/examples/streaming/objectdetection/
streaming_object_detection.py:1` + `image_path_writer.py:1`: one process
drops image paths into a monitored directory, the streaming job picks up
NEW path files per interval, detects, and writes visualized images named
by timestamp) re-hosted on the framework's runtime: a producer thread
spools path files, a micro-batch loop polls the spool dir with
`textFileStream` semantics (only files not seen before), and detections
render through the Visualizer. No Spark — directory polling plus a
predict call is what the streaming job amounted to.

    python examples/streaming_object_detection.py
"""

import os
import tempfile
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu import init_orca_context
from analytics_zoo_tpu.models import objectdetection as od
from analytics_zoo_tpu.models.detection_zoo import Visualizer

SIZE = 64
POLL_S = 0.1


def make_scene(rng):
    """White-rectangle 'car' on black — matching the detector's train
    distribution."""
    w, h = rng.randint(18, 32, 2)
    x1 = rng.randint(2, SIZE - w - 2)
    y1 = rng.randint(2, SIZE - h - 2)
    img = np.zeros((SIZE, SIZE, 3), np.uint8)
    img[y1:y1 + h, x1:x1 + w] = 255
    return img, (x1, y1, x1 + w, y1 + h)


def train_detector(seed=0, steps=120):
    """Tiny SSD trained on the synthetic scenes (the streaming job's
    'pretrained model' role — reference loads a downloaded SSD)."""
    import optax
    rng = np.random.RandomState(seed)
    xs, boxes = [], []
    for _ in range(24):
        img, bb = make_scene(rng)
        xs.append(img.astype(np.float32) / 255.0)
        boxes.append(bb)
    x = np.stack(xs)
    gt_boxes = np.asarray(boxes, np.float32)[:, None, :] / SIZE
    gt_labels = np.ones((len(xs), 1), np.int32)

    model, anchors = od.build_ssd(n_classes=2, image_size=SIZE)
    n_per_map = [8 * 8 * 3, 4 * 4 * 3]
    params = model.build(jax.random.PRNGKey(0))
    labels, loc_t, matched = jax.vmap(
        lambda b, l: od.match_anchors(b, l, jnp.asarray(anchors)))(
            jnp.asarray(gt_boxes), jnp.asarray(gt_labels))
    opt = optax.adam(3e-3)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state):
        def loss_fn(p):
            flat = model.apply(p, jnp.asarray(x))
            loc, conf = od.split_ssd_output(flat, n_per_map, 2)
            return od.multibox_loss(conf, loc, labels, loc_t, matched)
        l, g = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = opt.update(g, opt_state)
        return optax.apply_updates(params, updates), opt_state, l

    for _ in range(steps):
        params, opt_state, l = step(params, opt_state)
    model.params = jax.device_get(params)
    return od.ObjectDetector(model, anchors, n_per_map, 2,
                             label_map={1: "car"})


def path_writer(img_dir, spool_dir, n_images, seed=7):
    """`image_path_writer.py` role: save images, then drop path-list
    files into the monitored spool dir, a few at a time."""
    import cv2
    rng = np.random.RandomState(seed)
    written = 0
    batch_idx = 0
    while written < n_images:
        k = min(int(rng.randint(1, 4)), n_images - written)
        paths = []
        for _ in range(k):
            img, _ = make_scene(rng)
            p = os.path.join(img_dir, f"img_{written:04d}.jpg")
            cv2.imwrite(p, cv2.cvtColor(img, cv2.COLOR_RGB2BGR))
            paths.append(p)
            written += 1
        # write-then-rename so the poller never reads half a file
        tmp = os.path.join(spool_dir, f".tmp_{batch_idx}")
        with open(tmp, "w") as fh:
            fh.write("\n".join(paths) + "\n")
        os.rename(tmp, os.path.join(spool_dir, f"batch_{batch_idx:04d}"))
        batch_idx += 1
        time.sleep(0.05)


def main():
    import cv2
    init_orca_context(cluster_mode="local")
    detector = train_detector()
    vis = Visualizer(label_map={1: "car"})

    img_dir = tempfile.mkdtemp(prefix="stream_imgs_")
    spool_dir = tempfile.mkdtemp(prefix="stream_spool_")
    out_dir = tempfile.mkdtemp(prefix="stream_out_")
    n_images = 12
    t = threading.Thread(target=path_writer,
                         args=(img_dir, spool_dir, n_images), daemon=True)
    t.start()

    seen_files = set()
    processed = hits = 0
    idle_polls = 0
    while idle_polls < 30:                       # ~3s of quiet = stream end
        new = sorted(f for f in os.listdir(spool_dir)
                     if not f.startswith(".") and f not in seen_files)
        if not new:
            idle_polls += 1
            time.sleep(POLL_S)
            continue
        idle_polls = 0
        paths = []
        for f in new:
            seen_files.add(f)
            with open(os.path.join(spool_dir, f)) as fh:
                paths += [ln.strip() for ln in fh if ln.strip()]
        imgs = np.stack([
            cv2.cvtColor(cv2.imread(p), cv2.COLOR_BGR2RGB)
            for p in paths]).astype(np.float32) / 255.0
        rows_per_img = detector.predict(imgs, score_threshold=0.3)
        for i, (p, rows) in enumerate(zip(paths, rows_per_img)):
            processed += 1
            hits += bool(rows)
            # reference names outputs by timestamp (the path is lost in
            # its NDArray stream); keep a counter for uniqueness
            stamp = f"{time.time():.6f}".replace(".", "")[:14]
            out = os.path.join(out_dir, f"det_{stamp}_{processed}.jpg")
            canvas = vis.draw((imgs[i] * 255).astype(np.uint8), rows[:3])
            cv2.imwrite(out, cv2.cvtColor(canvas, cv2.COLOR_RGB2BGR))
        print(f"micro-batch: {len(paths)} image(s), "
              f"{processed}/{n_images} processed")
    t.join(timeout=5)

    outs = os.listdir(out_dir)
    print(f"stream done: {processed} images, {hits} with detections, "
          f"{len(outs)} rendered files in {out_dir}")
    assert processed == n_images
    assert hits >= int(0.8 * n_images), f"detector missed too much: {hits}"
    assert len(outs) == n_images
    print("OK")


if __name__ == "__main__":
    main()
