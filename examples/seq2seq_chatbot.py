"""Sequence-to-sequence training + greedy inference (the reference's Scala
chatbot example, `zoo/.../examples/chatbot/`, and `models/seq2seq/`). The
task: echo a per-step transformed copy of the input sequence.

    python examples/seq2seq_chatbot.py
"""

import numpy as np

from analytics_zoo_tpu import init_orca_context
from analytics_zoo_tpu.models.seq2seq import Seq2seq


def synthetic(n=256, t=8, f=6, seed=0):
    rng = np.random.RandomState(seed)
    enc = rng.rand(n, t, f).astype(np.float32)
    target = np.roll(enc, 1, axis=2) * 0.5  # deterministic mapping
    dec_in = np.concatenate(
        [np.zeros((n, 1, f), np.float32), target[:, :-1]], axis=1)
    return enc, dec_in, target


def main():
    init_orca_context(cluster_mode="local")
    enc, dec_in, target = synthetic()
    s2s = Seq2seq(rnn_type="lstm", encoder_hidden=(24,),
                  decoder_hidden=(24,), generator_units=6)
    s2s.compile("adam", "mse")
    s2s.fit([enc, dec_in], target, batch_size=64, nb_epoch=5)

    # teacher-forced eval
    mse = s2s.evaluate([enc, dec_in], target, batch_per_thread=64)
    print("teacher-forced metrics:", mse)

    # autoregressive greedy decode from a zero start token
    start = np.zeros((4, 6), np.float32)
    out = s2s.infer(enc[:4], start, max_seq_len=8)
    print("decoded shape:", np.asarray(out).shape)


if __name__ == "__main__":
    main()
