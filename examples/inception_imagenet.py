"""ImageNet-scale image-classification training: Inception-v1 / ResNet-50
from TFRecord shards — the reference's headline training workload
(`pyzoo/zoo/examples/inception/inception.py:1`, Scala
`examples/inception/ImageNet2012.scala:1` + `Train.scala`; scaling claim
`docs/docs/wp-bigdl.md:164`).

Composes the full input path at real-image scale: JPEG-encoded TFRecord
shards → streaming reader (C++ scanner) → THREADED decode + augmentation
(the parallel shard pipeline, `data/pipeline.py`, through
`from_tfrecord(num_workers=...)`: bounded record-range shards decode on
the pool behind a deterministic reorder buffer; JPEG decode and cv2 ops
release the GIL) → shuffle window → static-shape batches →
`Estimator.fit` with the prefetch pipeline overlapping host→device
transfer.

Logs the pipeline-vs-chip budget: mean producer time per batch (measured
inside the iterator the prefetch thread drains) against the mean
end-to-end step time. At steady state the step wall is
max(consumer, producer) with the prefetch overlap, so producer/step
strictly below 1 means the data pipeline is NEVER the binding constraint
— zero data-stall; the script prints that share plus images/s and fails
if the pipeline is within 90% of binding.

Synthetic fixture (default): class-separable JPEG thumbnails written as
`train-*` shards, so the example runs anywhere. Point it at a real corpus
(local disk or a gcsfuse-mounted bucket — the reader takes filesystem
paths) on a pod with:

    python examples/inception_imagenet.py \
        --data-dir /data/imagenet/train --image-size 224 \
        --model inception-v1 --batch 256 --workers 16
"""

from __future__ import annotations

import argparse
import os
import tempfile
import time

import numpy as np

from analytics_zoo_tpu import init_orca_context
from analytics_zoo_tpu.data import image as I
from analytics_zoo_tpu.data import tfrecord as tfr
from analytics_zoo_tpu.data.dataset import TPUDataset
from analytics_zoo_tpu.learn.estimator import Estimator
from analytics_zoo_tpu.models.image import inception_v1, resnet


def write_fixture(out_dir: str, n_shards: int, per_shard: int,
                  classes: int, size: int) -> None:
    """Class-separable JPEG corpus in ImageNet TFRecord layout
    (`image/encoded` JPEG bytes + `image/class/label`)."""
    import cv2
    rs = np.random.RandomState(0)
    for s in range(n_shards):
        recs = []
        for _ in range(per_shard):
            label = rs.randint(classes)
            img = np.empty((size + size // 4, size + size // 4, 3), np.uint8)
            img[...] = (label * (224 // classes) + 16,
                        255 - label * (224 // classes), 96)
            img[::3 + label] = 255 - img[::3 + label]        # class texture
            img = np.clip(img.astype(np.int32)
                          + rs.randint(0, 24, img.shape), 0,
                          255).astype(np.uint8)
            ok, enc = cv2.imencode(".jpg", img)
            assert ok
            recs.append(tfr.encode_example({
                "image/encoded": enc.tobytes(),
                "image/class/label": np.asarray([label], np.int64),
            }))
        tfr.write_tfrecord(
            os.path.join(out_dir, f"train-{s:05d}-of-{n_shards:05d}"), recs)


def make_parse_fn(size: int, classes: int, seed: int = 0):
    """JPEG decode + the reference inception augmentation chain: aspect
    scale to a slightly larger short side, random crop + mirror
    (`ImageNet2012.scala` train transformer). The output stays uint8 —
    normalization runs ON DEVICE (`normalize_layer`), so host→device
    ships 1 byte per pixel instead of 4 (the standard TPU input-pipeline
    design; 224² batches are transfer-bound otherwise)."""
    import cv2
    scale = I.ImageAspectScale(size + size // 8)
    crop = I.ImageRandomCropper(size, size, mirror=True, seed=seed)

    def parse(ex):
        raw = np.frombuffer(ex["image/encoded"][0], np.uint8)
        img = cv2.cvtColor(cv2.imdecode(raw, cv2.IMREAD_COLOR),
                           cv2.COLOR_BGR2RGB)
        img = scale(img)
        if min(img.shape[:2]) < size:
            # extreme aspect ratios: AspectScale's long-side cap can push
            # the short side under the crop — fall back to a square
            # resize instead of crashing the epoch
            img = cv2.resize(img, (size + size // 8, size + size // 8))
        label = int(ex["image/class/label"][0]) % classes
        return crop(img).astype(np.uint8), np.int32(label)

    return parse


def normalize_layer():
    """On-device per-channel ImageNet normalization of uint8 inputs."""
    import jax.numpy as jnp
    from analytics_zoo_tpu.ops.autograd import Lambda
    mean = jnp.asarray([123.0, 117.0, 104.0], jnp.float32)
    std = jnp.asarray([58.4, 57.1, 57.4], jnp.float32)
    return Lambda(lambda x: (jnp.asarray(x, jnp.float32) - mean) / std)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data-dir", default=None,
                    help="TFRecord dir/glob (default: synthetic fixture)")
    ap.add_argument("--model", default="inception-v1",
                    choices=["inception-v1", "resnet-50", "resnet-18"])
    ap.add_argument("--image-size", type=int, default=None,
                    help="default 224 for real data, 64 for the fixture")
    ap.add_argument("--classes", type=int, default=None)
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--epochs", type=int, default=1)
    ap.add_argument("--workers", type=int, default=8)
    ap.add_argument("--steps-per-run", type=int, default=4)
    ap.add_argument("--fixture-shards", type=int, default=4)
    ap.add_argument("--fixture-per-shard", type=int, default=64)
    args = ap.parse_args()

    init_orca_context(cluster_mode="local")
    tmp = None
    if args.data_dir is None:
        size = args.image_size or 64
        classes = args.classes or 4
        batch = args.batch or 32
        tmp = tempfile.TemporaryDirectory(prefix="imagenet_fixture_")
        write_fixture(tmp.name, args.fixture_shards, args.fixture_per_shard,
                      classes, size)
        data_glob = os.path.join(tmp.name, "train-*")
    else:
        size = args.image_size or 224
        classes = args.classes or 1000
        batch = args.batch or 256
        data_glob = args.data_dir

    ds = TPUDataset.from_tfrecord(
        data_glob, make_parse_fn(size, classes),
        batch_size=batch, shuffle_buffer=max(batch * 4, 256),
        num_workers=args.workers)
    # no n_samples() here: counting records header-walks every shard
    # (minutes over a fuse-mounted ImageNet) just for a log line
    n_shards = len(tfr.expand_files(data_glob))
    print(f"{n_shards} shard file(s), {args.workers} decode/augment "
          f"workers, batch {batch}, image {size}x{size}")

    from analytics_zoo_tpu.keras import Input, Model
    inp = Input(shape=(size, size, 3))
    h = normalize_layer()(inp)
    if args.model == "inception-v1":
        trunk = inception_v1(classes, (size, size, 3))
    else:
        depth = int(args.model.split("-")[1])
        trunk = resnet(depth, classes, (size, size, 3))
    model = Model(inp, trunk(h))
    est = Estimator.from_keras(model, optimizer="adam",
                               loss="sparse_categorical_crossentropy")

    # warm/compile on a bounded in-memory slice of exactly steps_per_run
    # batches (same shapes and scan length as the streamed run — NOT a
    # pass over the whole corpus, which at ImageNet scale would double a
    # 1-epoch benchmark)
    spr = args.steps_per_run
    warm = []
    for xb, yb, _ in ds.iter_train(1, seed=0):
        warm.append((xb, yb))
        if len(warm) == spr:
            break
    xw = np.concatenate([w[0] for w in warm])
    yw = np.concatenate([w[1] for w in warm])
    est.fit((xw, yw), batch_size=batch, epochs=1, steps_per_run=spr,
            mixed_precision=True)

    # producer timing shim: per-batch materialization time, accumulated in
    # the (single) prefetch thread that drains this iterator
    stats = {"stall_s": 0.0, "batches": 0}
    orig_iter = ds.iter_train

    def timed_iter(dp, seed=0):
        it = orig_iter(dp, seed)
        while True:
            t0 = time.perf_counter()
            try:
                item = next(it)
            except StopIteration:
                return
            stats["stall_s"] += time.perf_counter() - t0
            stats["batches"] += 1
            yield item

    ds.iter_train = timed_iter
    t0 = time.perf_counter()
    hist = est.fit(ds, epochs=args.epochs, steps_per_run=spr,
                   mixed_precision=True)
    dt = time.perf_counter() - t0

    steps = stats["batches"]
    imgs = steps * batch
    step_ms = dt / max(1, steps) * 1e3
    producer_ms = stats["stall_s"] / max(1, steps) * 1e3
    # steady-state step wall = max(consumer, producer) under prefetch:
    # producer strictly under the step cycle => zero data-stall
    share = producer_ms / max(step_ms, 1e-9)
    print(f"loss {hist['loss'][-1]:.4f}")
    print(f"throughput: {imgs / dt:.1f} images/s "
          f"({step_ms:.1f} ms/step end-to-end)")
    print(f"pipeline: producer {producer_ms:.1f} ms/batch vs step "
          f"{step_ms:.1f} ms -> input-pipeline share {share * 100:.0f}% "
          f"(data-stall 0 while < 100%)")
    assert share <= 0.9, (
        f"input pipeline is (nearly) the bottleneck: producer "
        f"{producer_ms:.1f} ms/batch vs step {step_ms:.1f} ms; raise "
        f"--workers")
    if tmp is not None:
        tmp.cleanup()
    print("OK")


if __name__ == "__main__":
    main()
