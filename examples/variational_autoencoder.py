"""Variational autoencoder (the reference's `apps/variational-autoencoder/`
notebooks) built from the functional API + GaussianSampler reparameterization
layer, trained with a CustomLoss combining reconstruction + KL.

    python examples/variational_autoencoder.py
"""

import numpy as np

from analytics_zoo_tpu import init_orca_context
from analytics_zoo_tpu.keras import Input, Model
from analytics_zoo_tpu.keras import layers as L


def synthetic_digits(n=512, d=64, seed=0):
    """Two latent factors → observable via fixed random projection."""
    rng = np.random.RandomState(seed)
    z = rng.randn(n, 2).astype(np.float32)
    proj = rng.randn(2, d).astype(np.float32)
    x = np.tanh(z @ proj) + 0.05 * rng.randn(n, d).astype(np.float32)
    return x.astype(np.float32)


def main():
    init_orca_context(cluster_mode="local")
    x = synthetic_digits()
    latent = 2

    inp = Input(shape=(64,))
    h = L.Dense(32, activation="relu", name="enc1")(inp)
    z_mean = L.Dense(latent, name="z_mean")(h)
    z_log_var = L.Dense(latent, name="z_log_var")(h)
    z = L.GaussianSampler(name="sampler")([z_mean, z_log_var])
    dh = L.Dense(32, activation="relu", name="dec1")(z)
    recon = L.Dense(64, name="recon")(dh)
    # outputs: reconstruction + the latent stats the loss needs
    vae = Model(inp, [recon, z_mean, z_log_var])

    def vae_loss(y_true, y_pred):
        import jax.numpy as jnp
        recon_out, mu, log_var = y_pred
        xt = y_true[0]
        rec = jnp.mean(jnp.sum((recon_out - xt) ** 2, axis=1))
        kl = -0.5 * jnp.mean(jnp.sum(
            1 + log_var - mu ** 2 - jnp.exp(log_var), axis=1))
        return rec + 0.1 * kl

    vae.compile("adam", vae_loss)
    hist = vae.fit([x], [x, x[:, :2] * 0, x[:, :2] * 0],
                   batch_size=64, nb_epoch=10)
    print("final VAE loss:", round(hist["loss"][-1], 3))

    recon_out, mu, _ = vae.predict(x[:8], batch_per_thread=8)
    err = float(np.mean((np.asarray(recon_out) - x[:8]) ** 2))
    print(f"reconstruction mse on held-out rows: {err:.4f}")
    print("latent means shape:", np.asarray(mu).shape)


if __name__ == "__main__":
    main()
