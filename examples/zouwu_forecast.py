"""Zouwu time-series forecasting + anomaly thresholding (the reference's
`pyzoo/zoo/zouwu/` forecasters and ThresholdDetector).

    python examples/zouwu_forecast.py [--model lstm|tcn|seq2seq|mtnet]
"""

import argparse

import numpy as np

from analytics_zoo_tpu import init_orca_context
from analytics_zoo_tpu.models.anomalydetection import ThresholdDetector
from analytics_zoo_tpu.zouwu.forecast import (
    LSTMForecaster, MTNetForecaster, Seq2SeqForecaster, TCNForecaster)


def rolling(series, past, horizon=1):
    n = len(series) - past - horizon + 1
    x = np.stack([series[i:i + past] for i in range(n)])[..., None]
    y = np.stack([series[i + past:i + past + horizon] for i in range(n)])
    return x.astype(np.float32), y.astype(np.float32)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="lstm",
                    choices=["lstm", "tcn", "seq2seq", "mtnet"])
    args = ap.parse_args()

    init_orca_context(cluster_mode="local")
    t = np.arange(600)
    series = (np.sin(2 * np.pi * t / 24)
              + 0.05 * np.random.RandomState(0).randn(600)).astype(np.float32)

    past = 48
    if args.model == "lstm":
        fc = LSTMForecaster(past_seq_len=past, feature_dim=1,
                            lstm_1_units=16, lstm_2_units=8)
    elif args.model == "tcn":
        fc = TCNForecaster(past_seq_len=past, feature_dim=1, target_dim=1)
    elif args.model == "seq2seq":
        fc = Seq2SeqForecaster(past_seq_len=past, feature_dim=1,
                               target_dim=1)
    else:
        fc = MTNetForecaster(target_dim=1, feature_dim=1,
                             long_series_num=4, series_length=12)
        past = fc.past_seq_len

    x, y = rolling(series, past)
    n_train = int(len(x) * 0.8)
    fc.fit(x[:n_train], y[:n_train], epochs=3, batch_size=64)
    pred = fc.predict(x[n_train:]).reshape(-1)
    truth = y[n_train:].reshape(-1)
    print("eval:", fc.evaluate(x[n_train:], y[n_train:],
                               metrics=("mse", "mae")))

    det = ThresholdDetector(ratio=0.02)
    det.fit(truth, pred)
    flags = det.score(truth, pred)
    print(f"threshold detector flagged {int(flags.sum())} points")


if __name__ == "__main__":
    main()
