"""Serving latency benchmark — p50/p99 end-to-end through the broker.

BASELINE.md target: p50 < 50 ms for the batched TPU InferenceModel behind
the Redis queue. The same workload runs through THREE broker paths and
reports each (the reference measures through Redis,
`docker/cluster-serving/perf/offline-benchmark:1-25`):

- memory: in-process MemoryBroker (stack floor: encode/batch/jit/decode)
- tcp:    TCPBrokerServer over a localhost socket
- redis:  RedisBroker speaking real RESP2 to the in-package
          MiniRedisServer over a localhost socket — the wire path a
          production Redis would serve; the headline number.

Note on dev rigs with a remote-tunneled TPU (axon): every device call pays
the tunnel's HTTP round trip (~100 ms), which dominates. A real v5e host
runs the model in-process; set JAX_PLATFORMS=cpu to measure the serving
stack itself.

    python bench_serving.py
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax

# honor JAX_PLATFORMS=cpu even though the machine's sitecustomize
# preimports jax with the TPU plugin pinned (backends init lazily, so the
# live-config update still takes effect — see .claude/skills/verify)
if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
    jax.config.update("jax_platforms", "cpu")

import numpy as np


N_REQUESTS = 200


def _measure(infer, broker_kind: str, n: int = N_REQUESTS):
    from analytics_zoo_tpu.serving.broker import (MemoryBroker, TCPBroker,
                                                  TCPBrokerServer)
    from analytics_zoo_tpu.serving.client import InputQueue, OutputQueue
    from analytics_zoo_tpu.serving.redis_server import MiniRedisServer
    from analytics_zoo_tpu.serving.server import ClusterServing

    server = None
    if broker_kind == "memory":
        serve_broker = client_broker = MemoryBroker()
    elif broker_kind == "tcp":
        server = TCPBrokerServer().start()
        serve_broker = TCPBroker(server.host, server.port)
        client_broker = TCPBroker(server.host, server.port)
    elif broker_kind == "redis":
        from analytics_zoo_tpu.serving.broker import RedisBroker
        server = MiniRedisServer().start()
        serve_broker = RedisBroker(server.host, server.port)
        client_broker = RedisBroker(server.host, server.port)
    else:
        raise ValueError(broker_kind)

    serving = ClusterServing(infer, broker=serve_broker, batch_size=32,
                             batch_timeout_ms=2).start()
    inq = InputQueue(client_broker)
    outq = OutputQueue(client_broker)

    img = np.random.rand(32, 32, 3).astype(np.float32)
    lat = []
    for _ in range(n):
        t0 = time.perf_counter()
        uri = inq.enqueue(t=img)
        while True:
            res = outq.query(uri, delete=True)
            if res is not None:
                break
            time.sleep(0.0005)
        lat.append((time.perf_counter() - t0) * 1e3)
    serving.stop()
    for br in (serve_broker, client_broker):
        if hasattr(br, "close"):
            br.close()
    if server is not None:
        server.stop()
    lat = np.asarray(sorted(lat))
    return (float(np.percentile(lat, 50)), float(np.percentile(lat, 99)))


def _serving_model():
    from analytics_zoo_tpu.keras import Sequential
    from analytics_zoo_tpu.keras import layers as L
    model = Sequential([
        L.Convolution2D(16, 3, 3, input_shape=(32, 32, 3),
                        border_mode="same", activation="relu"),
        L.MaxPooling2D(),
        L.Convolution2D(32, 3, 3, border_mode="same", activation="relu"),
        L.GlobalAveragePooling2D(),
        L.Dense(10, activation="softmax"),
    ])
    model.ensure_built(np.zeros((1, 32, 32, 3), np.float32))
    return model


def _device_forward_main():
    """BENCH_DEVICE_FORWARD=1: the model's batched forward ON THE TPU,
    tunnel excluded (VERDICT r4 #3). A single dispatch through the dev
    tunnel costs ~100 ms of HTTP round trip that a production v5e host
    (model in-process) never pays, so per-forward device time is measured
    the same way the training bench does: chain k forwards with a data
    dependency inside one jitted fori_loop, read back once, divide by k.
    Percentiles are over repeated trials (sustained-forward latency).
    Also measures the int8-quantized forward (serving/quantization.py)
    for the OpenVINO-int8-parity speedup number."""
    import jax.numpy as jnp

    from analytics_zoo_tpu import init_orca_context
    from analytics_zoo_tpu.serving.quantization import quantize_model_params

    init_orca_context(cluster_mode="local")
    model = _serving_model()
    batch = int(os.environ.get("BENCH_SERVE_BATCH", 32))
    # k sized so per-trial COMPUTE dwarfs the ±10 ms swing of the ~120 ms
    # RTT being subtracted: the tiny CNN runs ~10 µs/forward, so the old
    # k=2000 left ±5 µs of RTT noise on a 10 µs measurement — published
    # p50s went NEGATIVE in noisy windows. 20000 forwards ≈ 0.2 s of
    # compute → ±0.5 µs.
    k, trials = 20000, 10
    x0 = jnp.asarray(np.random.rand(batch, 32, 32, 3).astype(np.float32))

    # dispatch+readback round trip, re-probed ADJACENT to each timed
    # section; subtract the MINIMUM observed (same rationale as the mlp
    # A/B below: percentile/min estimators pick low-RTT draws, so
    # subtracting a stale median over-subtracts)
    @jax.jit
    def empty(x):
        return jnp.sum(x[0, 0, 0])

    def probe_rtt(n=10):
        float(empty(x0))
        vals = []
        for _ in range(n):
            t0 = time.perf_counter()
            float(empty(x0))
            vals.append(time.perf_counter() - t0)
        return vals

    def chained(params):
        @jax.jit
        def run(x):
            def body(_, carry):
                x, acc = carry
                out = model.apply(params, x, training=False)
                # data dependency so XLA cannot elide iterations
                return (x + 1e-12 * jnp.mean(out), acc + jnp.sum(out))
            return jax.lax.fori_loop(0, k, body, (x, 0.0))
        run(x0)[1].block_until_ready()
        float(run(x0)[1])                  # forced readback (warm)
        rtt = min(probe_rtt())
        lat = []
        for _ in range(trials):
            t0 = time.perf_counter()
            float(run(x0)[1])
            lat.append((time.perf_counter() - t0 - rtt) * 1e3 / k)
        if min(lat) <= 0:
            # a congestion spike made the probe exceed a trial's wall
            # time: the data is nonsense — publish null, not 0.0
            return None, None
        # percentiles keep ±(RTT swing)/k ≈ ±0.5 µs of residual noise in
        # p99 (per-trial RTT is unknowable); ~5% on this forward, stated
        # rather than hidden
        lat = np.asarray(sorted(lat))
        return (float(np.percentile(lat, 50)),
                float(np.percentile(lat, 99)))

    rtts = probe_rtt()
    _rtt = float(np.median(rtts))

    f32_params = model.params
    p50, p99 = chained(f32_params)
    q_params = quantize_model_params(model, jax.device_get(f32_params))
    q_params = jax.device_put(q_params)
    p50_q, p99_q = chained(q_params)

    # int8's speedup case is DENSE stacks (the OpenVINO-int8 workload
    # class); the tiny serving CNN above is compute-trivial so its int8
    # delta is noise. Measure a 4096-wide classifier head, f32 vs bf16
    # vs int8. NOTE on regime: inside the chained loop the weights are
    # loop-invariant, so XLA keeps them hot (hoisted conversions /
    # on-chip residency) — this measures STEADY-STATE serving under
    # load (weights resident, activations streaming), where int8's win
    # is the MXU's 2x int8 rate, not weight-fetch bandwidth.
    from analytics_zoo_tpu.keras import Sequential
    from analytics_zoo_tpu.keras import layers as L
    mlp = Sequential([
        L.Dense(4096, activation="relu", input_shape=(4096,)),
        L.Dense(4096, activation="relu"),
        L.Dense(4096, activation="relu"),
        L.Dense(1000, activation="softmax")])
    mlp.ensure_built(np.zeros((1, 4096), np.float32))
    x_mlp = jnp.asarray(np.random.rand(128, 4096).astype(np.float32))

    # k large enough that per-config compute (int8 ≈ 0.09, bf16 ≈ 0.18
    # ms/forward → 0.35-0.7 s per trial) dwarfs the ±10 ms swing of the
    # ~120 ms tunnel RTT being subtracted: at the old k=500 the int8
    # trial was ~45 ms of compute against that swing and the "speedup"
    # field bounced between 1.0x and 12.7x run to run — RTT noise
    k_mlp = 4000

    def make_run(params):
        @jax.jit
        def run(x):
            def body(_, carry):
                x, acc = carry
                out = mlp.apply(params, x, training=False)
                return (x + 1e-12 * jnp.mean(out), acc + jnp.sum(out))
            return jax.lax.fori_loop(0, k_mlp, body, (x, 0.0))
        float(run(x_mlp)[1])                 # warm/compile
        return run

    runs = {
        "f32": make_run(mlp.params),
        "bf16": make_run(jax.tree_util.tree_map(
            lambda a: a.astype(jnp.bfloat16), mlp.params)),
        "int8": make_run(jax.device_put(
            quantize_model_params(mlp, jax.device_get(mlp.params)))),
    }
    # interleaved A/B/C rounds, min-of-N per config: the tunnel chip's
    # minute-scale throughput drift would otherwise bias sequential blocks
    best = {kname: float("inf") for kname in runs}
    for _ in range(6):
        for kname, run in runs.items():
            t0 = time.perf_counter()
            float(run(x_mlp)[1])
            best[kname] = min(best[kname], time.perf_counter() - t0)
    # re-probe the RTT ADJACENT to the A/B loop and subtract the MINIMUM
    # of those FRESH samples only (a stale low-RTT draw from the startup
    # probe would over-subtract): min-of-6 wall times preferentially
    # pick low-RTT draws, so subtracting a median over-subtracts — a
    # constant absolute bias that the fastest config (int8) pays
    # proportionally most, inflating the speedup
    rtt_min = min(probe_rtt())
    mlp_f32, mlp_bf16, mlp_q = (
        (best[kname] - rtt_min) * 1e3 / k_mlp
        for kname in ("f32", "bf16", "int8"))
    # a congested RTT probe larger than a config's wall time would yield
    # nonsense (negative, or astronomically clamped speedups): publish
    # null rather than a number no one should trust
    valid = min(mlp_f32, mlp_bf16, mlp_q) > 0

    rnd = lambda v: None if v is None else round(v, 3)  # noqa: E731
    print(json.dumps({
        "serving_device_forward_p50_ms": rnd(p50),
        "serving_device_forward_p99_ms": rnd(p99),
        "serving_device_forward_int8_p50_ms": rnd(p50_q),
        "serving_device_forward_int8_p99_ms": rnd(p99_q),
        "serving_device_batch": batch,
        "mlp4096_f32_ms": round(mlp_f32, 3) if valid else None,
        "mlp4096_bf16_ms": round(mlp_bf16, 3) if valid else None,
        "mlp4096_int8_ms": round(mlp_q, 3) if valid else None,
        # vs the BEST non-quantized config: with the terminal's
        # --xla_allow_excess_precision the "f32" matmuls already run at
        # bf16 rate and can measure at or under the cast-bearing bf16
        # tree, so bf16-only would flatter int8
        "serving_int8_speedup": (round(min(mlp_f32, mlp_bf16) / mlp_q, 2)
                                 if valid else None),
        "device_dispatch_rtt_ms": round(_rtt * 1e3, 1),
        "device": getattr(jax.devices()[0], "device_kind",
                          str(jax.devices()[0])),
    }))


def main():
    from analytics_zoo_tpu import init_orca_context, stop_orca_context
    from analytics_zoo_tpu.serving.inference_model import InferenceModel

    if os.environ.get("BENCH_DEVICE_FORWARD") == "1":
        return _device_forward_main()

    init_orca_context(cluster_mode="local")
    model = _serving_model()
    infer = InferenceModel(concurrent_num=2).load_keras(model)
    # warm every jit bucket the run will hit
    for b in (1, 2, 4, 8, 16, 32):
        infer.predict(np.zeros((b, 32, 32, 3), np.float32))

    results = {}
    for kind in ("memory", "tcp", "redis"):
        p50, p99 = _measure(infer, kind)
        results[kind] = {"p50_ms": round(p50, 2), "p99_ms": round(p99, 2)}

    # pure wire cost: identity model through the redis path, so the
    # composed TPU number (wire + device forward) never counts a model
    # forward twice
    ident = InferenceModel().load_fn(lambda p, x: x, params=())
    wire_p50, wire_p99 = _measure(ident, "redis")
    stop_orca_context()

    # headline: the Redis-wire path (what BASELINE.md names)
    p50 = results["redis"]["p50_ms"]
    print(json.dumps({
        "metric": "serving_p50_latency",
        "value": p50,
        "unit": "ms",
        "vs_baseline": round(50.0 / max(p50, 1e-6), 3),  # >1 beats target
        "broker": "redis",
        "p99_ms": results["redis"]["p99_ms"],
        "by_broker": results,
        "wire_only_p50_ms": round(wire_p50, 2),
        "wire_only_p99_ms": round(wire_p99, 2),
        "n_requests": N_REQUESTS,
    }))


if __name__ == "__main__":
    sys.exit(main())
