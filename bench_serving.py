"""Serving latency benchmark — p50/p99 end-to-end through the broker.

BASELINE.md target: p50 < 50 ms for the batched TPU InferenceModel behind
the Redis queue. The same workload runs through THREE broker paths and
reports each (the reference measures through Redis,
`docker/cluster-serving/perf/offline-benchmark:1-25`):

- memory: in-process MemoryBroker (stack floor: encode/batch/jit/decode)
- tcp:    TCPBrokerServer over a localhost socket
- redis:  RedisBroker speaking real RESP2 to the in-package
          MiniRedisServer over a localhost socket — the wire path a
          production Redis would serve; the headline number.

Note on dev rigs with a remote-tunneled TPU (axon): every device call pays
the tunnel's HTTP round trip (~100 ms), which dominates. A real v5e host
runs the model in-process; set JAX_PLATFORMS=cpu to measure the serving
stack itself.

    python bench_serving.py
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax

# honor JAX_PLATFORMS=cpu even though the machine's sitecustomize
# preimports jax with the TPU plugin pinned (backends init lazily, so the
# live-config update still takes effect — see .claude/skills/verify)
if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
    jax.config.update("jax_platforms", "cpu")

import numpy as np


N_REQUESTS = 200


def _measure(infer, broker_kind: str, n: int = N_REQUESTS):
    from analytics_zoo_tpu.serving.broker import (MemoryBroker, TCPBroker,
                                                  TCPBrokerServer)
    from analytics_zoo_tpu.serving.client import InputQueue, OutputQueue
    from analytics_zoo_tpu.serving.redis_server import MiniRedisServer
    from analytics_zoo_tpu.serving.server import ClusterServing

    server = None
    if broker_kind == "memory":
        serve_broker = client_broker = MemoryBroker()
    elif broker_kind == "tcp":
        server = TCPBrokerServer().start()
        serve_broker = TCPBroker(server.host, server.port)
        client_broker = TCPBroker(server.host, server.port)
    elif broker_kind == "redis":
        from analytics_zoo_tpu.serving.broker import RedisBroker
        server = MiniRedisServer().start()
        serve_broker = RedisBroker(server.host, server.port)
        client_broker = RedisBroker(server.host, server.port)
    else:
        raise ValueError(broker_kind)

    serving = ClusterServing(infer, broker=serve_broker, batch_size=32,
                             batch_timeout_ms=2).start()
    inq = InputQueue(client_broker)
    outq = OutputQueue(client_broker)

    img = np.random.rand(32, 32, 3).astype(np.float32)
    lat = []
    for _ in range(n):
        t0 = time.perf_counter()
        uri = inq.enqueue(t=img)
        while True:
            res = outq.query(uri, delete=True)
            if res is not None:
                break
            time.sleep(0.0005)
        lat.append((time.perf_counter() - t0) * 1e3)
    serving.stop()
    for br in (serve_broker, client_broker):
        if hasattr(br, "close"):
            br.close()
    if server is not None:
        server.stop()
    lat = np.asarray(sorted(lat))
    return (float(np.percentile(lat, 50)), float(np.percentile(lat, 99)))


def main():
    from analytics_zoo_tpu import init_orca_context, stop_orca_context
    from analytics_zoo_tpu.keras import Sequential
    from analytics_zoo_tpu.keras import layers as L
    from analytics_zoo_tpu.serving.inference_model import InferenceModel

    init_orca_context(cluster_mode="local")
    model = Sequential([
        L.Convolution2D(16, 3, 3, input_shape=(32, 32, 3),
                        border_mode="same", activation="relu"),
        L.MaxPooling2D(),
        L.Convolution2D(32, 3, 3, border_mode="same", activation="relu"),
        L.GlobalAveragePooling2D(),
        L.Dense(10, activation="softmax"),
    ])
    model.ensure_built(np.zeros((1, 32, 32, 3), np.float32))
    infer = InferenceModel(concurrent_num=2).load_keras(model)
    # warm every jit bucket the run will hit
    for b in (1, 2, 4, 8, 16, 32):
        infer.predict(np.zeros((b, 32, 32, 3), np.float32))

    results = {}
    for kind in ("memory", "tcp", "redis"):
        p50, p99 = _measure(infer, kind)
        results[kind] = {"p50_ms": round(p50, 2), "p99_ms": round(p99, 2)}
    stop_orca_context()

    # headline: the Redis-wire path (what BASELINE.md names)
    p50 = results["redis"]["p50_ms"]
    print(json.dumps({
        "metric": "serving_p50_latency",
        "value": p50,
        "unit": "ms",
        "vs_baseline": round(50.0 / max(p50, 1e-6), 3),  # >1 beats target
        "broker": "redis",
        "p99_ms": results["redis"]["p99_ms"],
        "by_broker": results,
        "n_requests": N_REQUESTS,
    }))


if __name__ == "__main__":
    sys.exit(main())
