"""Serving latency benchmark — p50/p99 end-to-end through the broker.

BASELINE.md target: p50 < 50 ms for the batched TPU InferenceModel behind
the Redis queue. The same workload runs through THREE broker paths and
reports each (the reference measures through Redis,
`docker/cluster-serving/perf/offline-benchmark:1-25`):

- memory: in-process MemoryBroker (stack floor: encode/batch/jit/decode)
- tcp:    TCPBrokerServer over a localhost socket
- redis:  RedisBroker speaking real RESP2 to the in-package
          MiniRedisServer over a localhost socket — the wire path a
          production Redis would serve; the headline number.

A closed-loop concurrent-client section measures SUSTAINED throughput
(what the single-in-flight p50 above cannot see): N client threads each
keep one request in flight against the pipelined engine (overlapped
decode/compute/sink, batched writeback) and against the old synchronous
loop on the same model — `serving_concurrent_rps_*` and the
`serving_pipeline_speedup` ratio. A warmup probe also reports post-
`warmup()` first-request latency vs steady-state p50 (no XLA compile on
the request path).

Note on dev rigs with a remote-tunneled TPU (axon): every device call pays
the tunnel's HTTP round trip (~100 ms), which dominates. A real v5e host
runs the model in-process; set JAX_PLATFORMS=cpu to measure the serving
stack itself.

    python bench_serving.py

A multi-device section (`--devices N`) drains the same backlog through 1,
2, ..., N model replicas (one per forced-host device; re-execs itself
with `--xla_force_host_platform_device_count=N` when needed) plus one
GSPMD-sharded copy, and reports the scaling curve, per-replica batch
counts, and efficiency. NOTE the host-core ceiling: forced-host "chips"
burn real CPU cores, so an M-core box caps replica scaling at ~M× no
matter how many virtual devices exist; a real pod's chips compute
off-host and scale to the device count. Both the raw curve and the
core-normalized efficiency are reported.

    python bench_serving.py --devices 8
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import threading
import time

import jax

# honor JAX_PLATFORMS=cpu even though the machine's sitecustomize
# preimports jax with the TPU plugin pinned (backends init lazily, so the
# live-config update still takes effect — see .claude/skills/verify)
if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
    jax.config.update("jax_platforms", "cpu")

import numpy as np


N_REQUESTS = 200


def _setup_brokers(broker_kind: str, n_clients: int = 1):
    """One serving-side connection plus `n_clients` client connections;
    returns (serve_broker, client_brokers, server_or_None)."""
    from analytics_zoo_tpu.serving.broker import (MemoryBroker, RedisBroker,
                                                  TCPBroker, TCPBrokerServer)
    from analytics_zoo_tpu.serving.redis_server import MiniRedisServer

    if broker_kind == "memory":
        br = MemoryBroker()
        return br, [br] * n_clients, None
    if broker_kind == "tcp":
        server = TCPBrokerServer().start()
        return (TCPBroker(server.host, server.port),
                [TCPBroker(server.host, server.port)
                 for _ in range(n_clients)], server)
    if broker_kind == "redis":
        server = MiniRedisServer().start()
        return (RedisBroker(server.host, server.port),
                [RedisBroker(server.host, server.port)
                 for _ in range(n_clients)], server)
    raise ValueError(broker_kind)


def _teardown_brokers(serve_broker, client_brokers, server):
    for br in [serve_broker] + list(client_brokers):
        if hasattr(br, "close"):
            br.close()
    if server is not None:
        server.stop()


def _measure(infer, broker_kind: str, n: int = N_REQUESTS):
    from analytics_zoo_tpu.serving.client import InputQueue, OutputQueue
    from analytics_zoo_tpu.serving.server import ClusterServing

    serve_broker, clients, server = _setup_brokers(broker_kind, 1)
    serving = ClusterServing(infer, broker=serve_broker, batch_size=32,
                             batch_timeout_ms=2).start()
    inq = InputQueue(clients[0])
    outq = OutputQueue(clients[0])

    img = np.random.rand(32, 32, 3).astype(np.float32)
    lat = []
    for _ in range(n):
        t0 = time.perf_counter()
        uri = inq.enqueue(t=img)
        while True:
            res = outq.query(uri, delete=True)
            if res is not None:
                break
            time.sleep(0.0005)
        lat.append((time.perf_counter() - t0) * 1e3)
    serving.stop()
    _teardown_brokers(serve_broker, clients, server)
    lat = np.asarray(sorted(lat))
    return (float(np.percentile(lat, 50)), float(np.percentile(lat, 99)))


def _measure_concurrent(infer, broker_kind: str, n_clients: int = 8,
                        total: int = 320, pipelined: bool = True,
                        batch_size: int = 32, sample=None):
    """Closed loop, `n_clients` logical clients: a request is submitted
    the moment one completes, keeping exactly `n_clients` in flight. One
    single-threaded loop drives all of them — per-client polling threads
    would measure GIL/poll churn, not the engine. Each sweep drains
    completed results with one `hgetall` + one batched delete, then
    backfills one submit per completion. Returns (sustained records/s,
    p50 ms, p99 ms)."""
    from analytics_zoo_tpu.serving.client import RESULT_KEY, InputQueue
    from analytics_zoo_tpu.serving.server import ClusterServing

    serve_broker, (submit_br, poll_br), server = _setup_brokers(
        broker_kind, 2)
    serving = ClusterServing(infer, broker=serve_broker,
                             batch_size=batch_size,
                             batch_timeout_ms=2,
                             pipelined=pipelined).start()
    img = sample if sample is not None \
        else np.random.rand(32, 32, 3).astype(np.float32)
    inq = InputQueue(submit_br)
    inflight = {}
    lat = []
    submitted = 0

    def submit():
        nonlocal submitted
        uri = inq.enqueue(t=img)
        inflight[uri] = time.perf_counter()
        submitted += 1

    t_wall = time.perf_counter()
    for _ in range(min(n_clients, total)):
        submit()
    deadline = time.time() + 120
    while len(lat) < total and time.time() < deadline:
        allr = poll_br.hgetall(RESULT_KEY)
        done = [u for u in allr if u in inflight]
        if not done:
            time.sleep(0.001)
            continue
        now = time.perf_counter()
        poll_br.hdel_many(RESULT_KEY, done)
        for uri in done:
            lat.append((now - inflight.pop(uri)) * 1e3)
            if submitted < total:
                submit()
    t_wall = time.perf_counter() - t_wall
    serving.stop()
    _teardown_brokers(serve_broker, [submit_br, poll_br], server)
    if not lat:
        return 0.0, float("nan"), float("nan")
    arr = np.asarray(sorted(lat))
    return (len(lat) / t_wall,
            float(np.percentile(arr, 50)), float(np.percentile(arr, 99)))


def _measure_drain(infer, broker_kind: str, total: int = 480,
                   pipelined: bool = True, batch_size: int = 32,
                   sample=None):
    """Engine-limited throughput: pre-fill the stream with `total`
    records, start the engine, time until every result lands. Client
    costs are excluded (the backlog already exists), so unlike the
    closed loop this is stable run-to-run and measures the serving
    engine itself."""
    from analytics_zoo_tpu.serving.client import RESULT_KEY, InputQueue
    from analytics_zoo_tpu.serving.server import ClusterServing

    serve_broker, (submit_br, poll_br), server = _setup_brokers(
        broker_kind, 2)
    img = sample if sample is not None \
        else np.random.rand(32, 32, 3).astype(np.float32)
    inq = InputQueue(submit_br)
    for _ in range(total):
        inq.enqueue(t=img)
    serving = ClusterServing(infer, broker=serve_broker,
                             batch_size=batch_size,
                             batch_timeout_ms=2,
                             pipelined=pipelined).start()
    t0 = time.perf_counter()
    ndone = 0
    deadline = time.time() + 120
    while ndone < total and time.time() < deadline:
        allr = poll_br.hgetall(RESULT_KEY)
        if allr:
            poll_br.hdel_many(RESULT_KEY, list(allr))
            ndone += len(allr)
        else:
            time.sleep(0.001)
    dt = time.perf_counter() - t0
    serving.stop()
    _teardown_brokers(serve_broker, [submit_br, poll_br], server)
    return ndone / dt


def _measure_decode_ab(infer, total: int = 480, rounds: int = 3):
    """Decode-share A/B (ISSUE 9 satellite): the ~0.24 ms host-side gap
    between `serving_p50_ms` and wire-only p50 is decode + dispatch
    work; zero-copy decode writes each record straight into a
    preallocated bucket-shaped batch buffer (no per-record ndarray, no
    dispatch-stage np.stack). Engine-limited drain per mode, reading
    each ENGINE'S OWN stage timers (fresh per ClusterServing, so the
    two modes can't contaminate each other's percentiles). Interleaved
    rounds + per-mode MEDIAN, like the concurrent bench: a single
    drain's percentiles ride whatever the host scheduler did that
    second (first-round cold starts measured 2x on the 2-core rig)."""
    from analytics_zoo_tpu.serving.client import RESULT_KEY, InputQueue
    from analytics_zoo_tpu.serving.server import ClusterServing

    runs = {"legacy": [], "zero_copy": []}
    for _ in range(rounds):
        for label, zero_copy in (("legacy", False), ("zero_copy", True)):
            serve_broker, (submit_br, poll_br), server = _setup_brokers(
                "redis", 2)
            inq = InputQueue(submit_br)
            img = np.random.rand(32, 32, 3).astype(np.float32)
            for _ in range(total):
                inq.enqueue(t=img)
            serving = ClusterServing(infer, broker=serve_broker,
                                     batch_size=32, batch_timeout_ms=2,
                                     pipelined=True,
                                     zero_copy_decode=zero_copy).start()
            t0 = time.perf_counter()
            ndone = 0
            deadline = time.time() + 120
            while ndone < total and time.time() < deadline:
                allr = poll_br.hgetall(RESULT_KEY)
                if allr:
                    poll_br.hdel_many(RESULT_KEY, list(allr))
                    ndone += len(allr)
                else:
                    time.sleep(0.001)
            dt = time.perf_counter() - t0
            stages = {name: t.snapshot() for name, t in
                      (("decode", serving.decode_timer),
                       ("dispatch", serving.dispatch_timer))}
            serving.stop()
            _teardown_brokers(serve_broker, [submit_br, poll_br], server)
            runs[label].append((ndone / dt, stages["decode"]["p50_ms"],
                                stages["dispatch"]["p50_ms"]))
    out = {}
    for label, rows in runs.items():
        out[label] = {
            "drain_rps": round(float(np.median([r[0] for r in rows])), 1),
            "decode_p50_ms": float(np.median([r[1] for r in rows])),
            "dispatch_p50_ms": float(np.median([r[2] for r in rows])),
        }
    host = out["legacy"]["decode_p50_ms"] + out["legacy"]["dispatch_p50_ms"]
    zc = (out["zero_copy"]["decode_p50_ms"]
          + out["zero_copy"]["dispatch_p50_ms"])
    out["decode_dispatch_p50_cut_ms"] = round(host - zc, 4)
    return out


def _measure_trace_overhead(infer, total: int = 480, rounds: int = 3):
    """Trace-overhead A/B (ISSUE 17 satellite): engine-limited drain at
    head-sampling 0 / 0.01 / 1.0, fresh brokers + engine per mode per
    round so one mode's exporter thread can't ride in another's timing
    window. Interleaved rounds + per-mode MEDIAN, same estimator as the
    decode A/B — a single drain's rps rides host scheduling. The client
    stamps trace context at the matching rate (`InputQueue
    trace_sample`), so sampled drains pay the real wire cost too: the
    extra dict per record, the engine's wire/device/writeback spans,
    the hops row in each result, and the export thread. At full
    sampling the collector assembles a few finished requests from the
    published blobs — the `/trace/<id>` cost a debugging session
    actually pays."""
    from analytics_zoo_tpu.serving.client import RESULT_KEY, InputQueue
    from analytics_zoo_tpu.serving.server import ClusterServing
    from analytics_zoo_tpu.serving.trace_plane import TraceCollector

    modes = (("off", 0.0), ("1pct", 0.01), ("full", 1.0))
    runs = {label: [] for label, _ in modes}
    assembly_ms = []
    for _ in range(rounds):
        for label, rate in modes:
            serve_broker, (submit_br, poll_br), server = _setup_brokers(
                "redis", 2)
            inq = InputQueue(submit_br, trace_sample=rate)
            img = np.random.rand(32, 32, 3).astype(np.float32)
            uris = [inq.enqueue(t=img) for _ in range(total)]
            serving = ClusterServing(infer, broker=serve_broker,
                                     batch_size=32, batch_timeout_ms=2,
                                     pipelined=True, trace_sample=rate,
                                     trace_export_interval_s=0.2).start()
            t0 = time.perf_counter()
            ndone = 0
            deadline = time.time() + 120
            while ndone < total and time.time() < deadline:
                allr = poll_br.hgetall(RESULT_KEY)
                if allr:
                    poll_br.hdel_many(RESULT_KEY, list(allr))
                    ndone += len(allr)
                else:
                    time.sleep(0.001)
            dt = time.perf_counter() - t0
            serving.stop()        # flushes the exporter's final blob
            if label == "full":
                coll = TraceCollector(poll_br, "serving_stream")
                for uri in uris[:8]:
                    ta = time.perf_counter()
                    doc = coll.assemble(uri)
                    if doc.get("traceEvents"):
                        assembly_ms.append(
                            (time.perf_counter() - ta) * 1e3)
            _teardown_brokers(serve_broker, [submit_br, poll_br], server)
            runs[label].append(ndone / dt)
    out = {label: {"drain_rps": round(float(np.median(r)), 1)}
           for label, r in runs.items()}
    off = out["off"]["drain_rps"]
    out["overhead_1pct_pct"] = round(
        100.0 * (1.0 - out["1pct"]["drain_rps"] / max(off, 1e-9)), 2)
    out["overhead_full_pct"] = round(
        100.0 * (1.0 - out["full"]["drain_rps"] / max(off, 1e-9)), 2)
    if assembly_ms:
        out["assembly_p50_ms"] = round(float(np.median(assembly_ms)), 3)
    return out


def _trace_overhead_main(args) -> int:
    """--trace-overhead (ISSUE 17): the acceptance bound — 1% head
    sampling costs ≤ 2% of engine-limited drain throughput vs tracing
    off. Full (100%) sampling is reported beside it as the ceiling a
    debug session pays, plus the collector's assembly latency."""
    from analytics_zoo_tpu import init_orca_context, stop_orca_context
    from analytics_zoo_tpu.serving.inference_model import InferenceModel

    init_orca_context(cluster_mode="local")
    model = _serving_model()
    infer = InferenceModel(concurrent_num=2).load_keras(model)
    infer.warmup(np.zeros((32, 32, 3), np.float32),
                 buckets=[1, 2, 4, 8, 16, 32])
    ab = _measure_trace_overhead(infer, total=int(args.total) or 480)
    stop_orca_context()
    print(json.dumps({
        "metric": "serving_trace_overhead",
        "target_overhead_1pct_pct": 2.0,
        "trace_off_rps": ab["off"]["drain_rps"],
        "trace_1pct_rps": ab["1pct"]["drain_rps"],
        "trace_full_rps": ab["full"]["drain_rps"],
        "trace_overhead_1pct_pct": ab["overhead_1pct_pct"],
        "trace_overhead_full_pct": ab["overhead_full_pct"],
        "trace_assembly_p50_ms": ab.get("assembly_p50_ms"),
        "note": ("median of interleaved engine-limited drains per "
                 "sampling rate; negative overhead = host-scheduling "
                 "noise exceeded the tracing cost at this scale"),
    }))
    return 0


def _warmup_probe(model, replicas: int = 3):
    """Fresh InferenceModel + warmup(): is the FIRST request's latency
    within noise of steady-state (i.e. no compile on the request path)?
    Min over independent fresh replicas: a single first-request sample on
    a loaded box measures scheduler noise, while a compile on the request
    path would inflate EVERY replica's first request, so the min still
    detects it.

    The replicas share one persistent compile cache (a throwaway dir):
    the first pays the compiles and persists, the rest warm from disk —
    so the probe also reports how many buckets each restart compiled vs
    loaded (`warmup_source` counts)."""
    import shutil
    import tempfile

    from analytics_zoo_tpu.compile_cache import CompileCache
    from analytics_zoo_tpu.serving.inference_model import InferenceModel

    cache_dir = tempfile.mkdtemp(prefix="zoo-cc-probe-")
    x = np.random.rand(8, 32, 32, 3).astype(np.float32)  # exact bucket
    firsts, steadies = [], []
    sources = {"compiled": 0, "cached": 0, "jit": 0}
    try:
        cache = CompileCache(cache_dir)
        for _ in range(replicas):
            infer = InferenceModel(compile_cache=cache).load_keras(model)
            infer.warmup(np.zeros((32, 32, 3), np.float32),
                         buckets=[1, 2, 4, 8, 16, 32])
            for src in infer.warmup_source.values():
                sources[src] = sources.get(src, 0) + 1
            t0 = time.perf_counter()
            infer.predict(x)
            firsts.append((time.perf_counter() - t0) * 1e3)
            steady = []
            for _ in range(30):
                t0 = time.perf_counter()
                infer.predict(x)
                steady.append((time.perf_counter() - t0) * 1e3)
            steadies.append(float(np.percentile(np.asarray(steady), 50)))
    finally:
        shutil.rmtree(cache_dir, ignore_errors=True)
    return min(firsts), float(np.median(steadies)), sources


# -- multi-device: replica pool + sharded placement ------------------------

def _md_model(width: int = 512, iters: int = 32):
    """Compute-heavy-per-batch forward: a fori_loop of small (width x
    width) matmuls. Small matmuls keep XLA:CPU from spreading ONE
    execution across cores, so concurrent replicas — not intra-op
    threads — are the only way to use the whole machine; that mirrors a
    TPU pod, where each replica's compute runs off-host on its own chip.
    Returns (fn, params, one_record_sample)."""
    import jax.numpy as jnp
    rng = np.random.RandomState(0)
    W = (rng.randn(width, width).astype(np.float32) / np.sqrt(width))

    def fn(p, x):
        def body(_, c):
            return jnp.tanh(c @ p)
        return jax.lax.fori_loop(0, iters, body, x)

    return fn, W, rng.rand(width).astype(np.float32)


def multidevice_summary(n_devices: int, total: int = 256,
                        batch_size: int = 8, replica_counts=None,
                        closed_loop: bool = True) -> dict:
    """Backlog-drain scaling curve over the replica pool (requires
    `len(jax.devices()) >= n_devices`; see `--devices` for the re-exec
    wrapper). Per-replica batch counts come from the router's own
    book-keeping, so the JSON shows WHERE the work actually ran."""
    from analytics_zoo_tpu.serving.inference_model import InferenceModel

    fn, W, sample = _md_model()
    counts = sorted({c for c in (replica_counts or
                                 [1, 2, max(1, n_devices // 2), n_devices])
                     if 1 <= c <= n_devices})
    drain_rps, per_replica = {}, {}
    # every bucket the reader can form (straggler batches < batch_size
    # included) pre-compiles, or a mid-drain XLA compile pollutes the
    # scaling baseline
    def reachable(im):
        return [b for b in im.buckets if b <= batch_size] or im.buckets[:1]

    for r in counts:
        im = InferenceModel(num_replicas=r).load_fn(fn, W)
        im.warmup(sample, buckets=reachable(im))  # compile off the clock
        # best-of-2: an engine-limited drain is deterministic work, so
        # the max filters one-sided scheduler noise (the 2-core rigs
        # swing single runs 2-3x; a mean would keep the outlier). The
        # per-replica routing counts are the BEST run's delta, not the
        # sum over both — the JSON describes the run it publishes.
        best_rps, best_counts = 0.0, []
        for _ in range(2):
            before = [s["batches"] for s in im.replica_stats()]
            rps = _measure_drain(im, "memory", total=total,
                                 batch_size=batch_size, sample=sample)
            after = [s["batches"] for s in im.replica_stats()]
            if rps >= best_rps:
                best_rps = rps
                best_counts = [None if a is None else a - (b or 0)
                               for a, b in zip(after, before)]
        drain_rps[str(r)] = round(best_rps, 1)
        per_replica[str(r)] = best_counts
        im.close()

    ims = InferenceModel(placement="sharded").load_fn(fn, W)
    ims.warmup(sample, buckets=reachable(ims))
    sharded_rps = max(_measure_drain(ims, "memory", total=total,
                                     batch_size=batch_size, sample=sample)
                      for _ in range(2))

    base = drain_rps[str(counts[0])]
    best_r = max(drain_rps, key=lambda k: drain_rps[k])
    speedup = drain_rps[str(counts[-1])] / max(base, 1e-9)
    cores = os.cpu_count() or 1
    out = {
        "metric": "serving_multidevice_drain",
        "devices": n_devices,
        "host_cores": cores,
        "total_records": total,
        "batch_size": batch_size,
        "drain_rps": drain_rps,
        "drain_rps_sharded": round(sharded_rps, 1),
        "scaling_speedup": round(speedup, 2),
        "best_speedup": round(drain_rps[best_r] / max(base, 1e-9), 2),
        "best_replicas": int(best_r),
        "scaling_efficiency": round(speedup / n_devices, 3),
        # forced-host devices burn real cores: an M-core box caps replica
        # scaling at ~M x regardless of virtual device count. A real pod's
        # chips compute off-host, so there the ceiling IS the device count.
        "efficiency_vs_host_cores": round(
            speedup / min(n_devices, cores), 3),
        "per_replica_batches": per_replica,
        "note": ("forced-host devices share the host's cores: replica "
                 f"scaling here caps near {min(n_devices, cores)}x "
                 "(and oversubscribing threads past the core count can "
                 "degrade); on a real pod each chip computes off-host, "
                 "so the ceiling is the device count"),
    }
    if closed_loop:
        for label, r in (("1", 1), (str(n_devices), n_devices)):
            im = InferenceModel(num_replicas=r).load_fn(fn, W)
            im.warmup(sample, buckets=reachable(im))
            rps, p50, _p99 = _measure_concurrent(
                im, "memory", n_clients=4 * n_devices, total=total,
                batch_size=batch_size, sample=sample)
            out[f"closed_loop_rps_{label}"] = round(rps, 1)
            out[f"closed_loop_p50_ms_{label}"] = round(p50, 2)
            im.close()
    return out


def _multidevice_main(args) -> int:
    """`--devices N`: run `multidevice_summary` on an N-device platform,
    re-execing into a forced-host CPU child when this interpreter sees
    fewer devices (env must be set before jax initializes its backend —
    same pattern as `__graft_entry__._reexec_dryrun`)."""
    n = args.devices
    if len(jax.devices()) < n \
            and os.environ.get("_ZOO_MD_BENCH_CHILD") != "1":
        env = dict(os.environ)
        env["_ZOO_MD_BENCH_CHILD"] = "1"
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PALLAS_AXON_POOL_IPS", None)   # hermetic CPU child
        flags = env.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = (
                flags +
                f" --xla_force_host_platform_device_count={n}").strip()
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--devices", str(n), "--total", str(args.total)],
            env=env, cwd=os.path.dirname(os.path.abspath(__file__)),
            timeout=1800)
        return proc.returncode
    from analytics_zoo_tpu import init_orca_context, stop_orca_context
    init_orca_context(cluster_mode="local")
    summary = multidevice_summary(n, total=args.total)
    stop_orca_context()
    print(json.dumps(summary))
    return 0


# -- chaos: fault injection against a live engine (ISSUE 5) ----------------

def _chaos_summary(n_devices: int = 4, batch_size: int = 4) -> dict:
    """Drive the fault-tolerance layer with real faults and measure what
    an operator cares about: how fast a bad replica is quarantined, how
    fast it revives, whether a broker outage loses accepted records, and
    how much throughput survives after recovery.

    Acceptance (ISSUE 5): zero accepted-record loss, quarantine
    detection under 2 s, post-recovery drain throughput within 10% of
    the no-fault baseline."""
    from analytics_zoo_tpu.common import faults
    from analytics_zoo_tpu.serving.broker import MemoryBroker
    from analytics_zoo_tpu.serving.client import RESULT_KEY, InputQueue
    from analytics_zoo_tpu.serving.inference_model import InferenceModel
    from analytics_zoo_tpu.serving.server import ClusterServing

    fn, W, sample = _md_model(width=128, iters=8)
    im = InferenceModel(num_replicas=n_devices).load_fn(fn, W)
    im.warmup(sample,
              buckets=[b for b in im.buckets if b <= batch_size]
              or im.buckets[:1])
    broker = MemoryBroker(redeliver_after_s=2.0)
    serving = ClusterServing(
        im, broker=broker, batch_size=batch_size, batch_timeout_ms=2,
        failure_threshold=3, probe_interval_s=0.1, latency_factor=6.0,
        breaker_failure_threshold=2, breaker_reset_s=0.1).start()
    inq = InputQueue(broker)

    def collect(n, deadline_s=120.0, t0=None):
        """Wait for n results; returns (got, nans, seconds)."""
        t0 = time.perf_counter() if t0 is None else t0
        got = nans = 0
        deadline = time.time() + deadline_s
        while got < n and time.time() < deadline:
            allr = broker.hgetall(RESULT_KEY)
            if allr:
                broker.hdel_many(RESULT_KEY, list(allr))
                got += len(allr)
                nans += sum(1 for v in allr.values() if v == "NaN")
            else:
                time.sleep(0.002)
        return got, nans, time.perf_counter() - t0

    from analytics_zoo_tpu.serving.broker import encode_ndarray
    encoded = encode_ndarray(np.asarray(sample))

    def drain_rps(total=400):
        # engine-limited: the record payload is pre-encoded ONCE and
        # xadd'd raw, so the submit loop costs ~µs/record and the clock
        # (from first submit to last result) measures the ENGINE, not a
        # b64-encoding client contending for the same two cores
        import uuid
        t0 = time.perf_counter()
        for _ in range(total):
            broker.xadd(serving.stream,
                        {"uri": uuid.uuid4().hex, "data": {"t": encoded}})
        got, _nans, _dt = collect(total, t0=t0)
        return got / max(time.perf_counter() - t0, 1e-9)

    def feed_until(cond, timeout_s=20.0):
        """Steady singles until cond(); returns (elapsed or None, fed)."""
        t0 = time.monotonic()
        fed = 0
        while time.monotonic() - t0 < timeout_s:
            inq.enqueue(t=sample)
            fed += 1
            if cond():
                return time.monotonic() - t0, fed
            time.sleep(0.005)
        return None, fed

    def wait_healthy(n, timeout_s=30.0):
        t0 = time.monotonic()
        while im.healthy_replicas() < n:
            if time.monotonic() - t0 > timeout_s:
                return None
            time.sleep(0.01)
        return time.monotonic() - t0

    out = {"metric": "serving_chaos_record_loss", "unit": "records",
           "replicas": n_devices, "host_cores": os.cpu_count() or 1}

    # -- no-fault baseline (best of 3: single runs on a loaded 2-core
    # host swing ±2x one-sided; the max filters scheduler noise, same
    # estimator as multidevice_summary) ------------------------------------
    drain_rps()            # discarded: thread/executable warm-up drain
    baseline = max(drain_rps() for _ in range(3))

    # -- phase 1: replica crash → quarantine → revival ---------------------
    faults.inject("replica.dispatch",
                  faults.Fault(match=lambda c: c["replica"] == 1))
    detect_s, fed = feed_until(
        lambda: im.healthy_replicas() < n_devices)
    _got, crash_nans, _ = collect(fed, deadline_s=60)
    faults.clear("replica.dispatch")
    revive_s = wait_healthy(n_devices)
    out["quarantine_detect_s"] = round(detect_s, 3) if detect_s else None
    out["quarantine_revive_s"] = round(revive_s, 3) \
        if revive_s is not None else None
    out["crash_nan_results"] = crash_nans   # pre-quarantine degradations

    # -- phase 2: slow replica → latency-outlier quarantine ----------------
    faults.inject("replica.dispatch",
                  faults.Fault(mode="stall", delay_s=0.25,
                               match=lambda c: c["replica"] == 2))
    slow_s, fed = feed_until(
        lambda: im.healthy_replicas() < n_devices, timeout_s=30.0)
    collect(fed, deadline_s=60)
    faults.clear("replica.dispatch")
    wait_healthy(n_devices)
    out["slow_quarantine_detect_s"] = round(slow_s, 3) if slow_s else None

    # -- phase 3: broker outage → buffered writebacks, zero loss -----------
    from analytics_zoo_tpu.observability import get_registry
    shed = get_registry().get("serving_sink_shed_records_total")
    shed_before = shed.value() if shed else 0.0
    n_outage = 80
    for _ in range(30):
        inq.enqueue(t=sample)
    outage = faults.Fault(match=lambda c: c["role"] in ("reader", "sink"))
    faults.inject("broker.read_group", outage)
    faults.inject("broker.hset_many", outage)
    faults.inject("broker.ack", outage)
    threading.Timer(1.0, lambda: (faults.clear("broker.read_group"),
                                  faults.clear("broker.hset_many"),
                                  faults.clear("broker.ack"))).start()
    for _ in range(n_outage - 30):
        inq.enqueue(t=sample)
        time.sleep(0.002)
    got, outage_nans, _ = collect(n_outage, deadline_s=90)
    faults.clear()
    out["value"] = n_outage - got            # record loss — must be 0
    out["target"] = 0
    out["vs_baseline"] = 1.0 if got == n_outage else 0.0
    out["broker_outage_records"] = n_outage
    out["broker_outage_nans"] = outage_nans
    out["shed_records"] = round(
        (shed.value() if shed else 0.0) - shed_before, 1)

    # -- phase 4: post-recovery throughput (same best-of-3 estimator) ------
    post = max(drain_rps() for _ in range(3))
    out["baseline_drain_rps"] = round(baseline, 1)
    out["post_recovery_drain_rps"] = round(post, 1)
    out["post_recovery_ratio"] = round(post / max(baseline, 1e-9), 3)
    out["post_recovery_target"] = ">=0.9"

    serving.stop()
    im.close()
    return out


def _chaos_main(args) -> int:
    """`--chaos`: run `_chaos_summary` on a >=4-device platform,
    re-execing into a forced-host CPU child when needed (same pattern as
    `--devices`)."""
    n = max(4, getattr(args, "devices", None) or 4)
    if len(jax.devices()) < n \
            and os.environ.get("_ZOO_CHAOS_BENCH_CHILD") != "1":
        env = dict(os.environ)
        env["_ZOO_CHAOS_BENCH_CHILD"] = "1"
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PALLAS_AXON_POOL_IPS", None)   # hermetic CPU child
        flags = env.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = (
                flags +
                f" --xla_force_host_platform_device_count={n}").strip()
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--chaos"],
            env=env, cwd=os.path.dirname(os.path.abspath(__file__)),
            timeout=1800)
        return proc.returncode
    from analytics_zoo_tpu import init_orca_context, stop_orca_context
    init_orca_context(cluster_mode="local")
    summary = _chaos_summary(n)
    stop_orca_context()
    print(json.dumps(summary))
    return 0


# -- fleet: N engine processes behind one broker (ISSUE 10) ----------------

def _fleet_child(args) -> int:
    """One fleet engine, in its own process: build the compute-heavy
    model, warm through the SHARED compile cache (engine 1 compiles,
    the rest load — the fleet pays ~1 cold compile per bucket), report
    readiness, hold at the start gate, then join the consumer group
    under `--engine-id`, heartbeat, and drain until SIGTERM. SIGKILL
    (the chaos leg) is the point of the exercise: no cleanup runs, the
    PEL keeps this engine's unacked records, and a live peer's claim
    sweep adopts them.

    The ready-row/gate handshake (fleet:ready:<stream> /
    fleet:gate:<stream>) lets the parent prefill the WHOLE backlog
    before any engine consumes: without it the drain overlaps the
    parent's sequential xadd loop, engines run starved 1-2 record
    batches (predict p50 collapsed from 17 ms/8-rec batch to ~1.4 ms
    micro-batches when measured), and the curve benchmarks the
    prefill's contended xadd rate instead of fleet drain capacity."""
    import signal

    if args.pin_core is not None and hasattr(os, "sched_setaffinity"):
        # one core per engine (BEFORE jax sizes its threadpools): the
        # process-level analogue of forced-host devices — without it a
        # single engine's intra-op XLA threads saturate every core and
        # the fleet curve measures threadpool contention, not scaling
        try:
            os.sched_setaffinity(
                0, {args.pin_core % (os.cpu_count() or 1)})
        except OSError:
            pass

    from analytics_zoo_tpu import init_orca_context
    from analytics_zoo_tpu.compile_cache import CompileCache
    from analytics_zoo_tpu.serving.broker import connect_broker
    from analytics_zoo_tpu.serving.inference_model import InferenceModel
    from analytics_zoo_tpu.serving.server import ClusterServing

    init_orca_context(cluster_mode="local")
    # heavier per-record compute than the in-process multidevice bench:
    # the engine must be the limiter, not the pure-python MiniRedis
    # data plane (~2800 rec/s ceiling on this rig; a production Redis
    # is far above the curve). NARROW matmuls on purpose: at width 256
    # one execution stays on ONE thread (cpu/wall ~1.0 measured; 512
    # already spreads ~1.4 threads), so a single engine can't absorb
    # the whole host and fake the fleet baseline — essential where
    # sched_setaffinity isn't enforced (gVisor-style sandboxes accept
    # the call without binding). Same FLOPs/record as 512x256. The
    # forward reduces to ONE scalar per record so the writeback side
    # stays bytes-cheap too — drain scaling should measure compute,
    # not RESP serialization of 512-float rows.
    base_fn, W, sample = _md_model(width=256, iters=1024)
    rollout_version = None
    if args.rollout_dir:
        # chaos-rollout leg (ISSUE 14): the versioned weights come
        # from the published checkpoint dir, not the generator — every
        # engine starts on the newest PUBLISHED version and then
        # follows the controller's directives
        from analytics_zoo_tpu.learn.checkpoint import (
            latest_published_checkpoint, load_checkpoint)
        found = latest_published_checkpoint(args.rollout_dir)
        if found is None:
            raise SystemExit(
                f"no published checkpoint under {args.rollout_dir}")
        run_dir, rollout_version = found
        W, _, _ = load_checkpoint(run_dir, rollout_version)

    def fn(p, x):
        return base_fn(p, x).mean(axis=-1)
    cache = CompileCache(args.compile_cache_dir) \
        if args.compile_cache_dir else None
    im = InferenceModel(compile_cache=cache).load_fn(fn, W)
    batch = args.fleet_batch
    im.warmup(sample, buckets=[b for b in im.buckets if b <= batch]
              or im.buckets[:1])
    broker = connect_broker(args.broker_url)
    # construct BEFORE the gate (connections, registry wiring, replica
    # pool) so the timed drain window starts at reader-thread launch
    slo = {"latency_ms": args.slo_latency_ms, "latency_quantile": 0.99,
           "window_s": 10.0} if args.slo_latency_ms else None
    serving = ClusterServing(
        im, broker=broker, stream=args.stream,
        batch_size=batch, batch_timeout_ms=args.batch_timeout_ms,
        engine_id=args.engine_id,
        claim_min_idle_s=args.claim_min_idle,
        claim_interval_s=max(args.claim_min_idle / 4.0, 0.1),
        heartbeat_interval_s=0.25,
        # elastic knobs (ISSUE 11): the --elastic replay runs adaptive
        # deadline-aware engines against "static" pad-to-largest ones
        batch_policy=args.batch_policy,
        deadline_ms=args.deadline_ms or None,
        slo=slo, model_version=rollout_version,
        # request-plane knobs (ISSUE 16): the --request-plane scaling
        # leg runs p engines over p partition streams; default 1 keeps
        # every other leg on the legacy unsuffixed stream
        partitions=args.partitions,
        partition_lease_ttl_s=args.partition_lease_ttl)
    broker.hset(f"fleet:ready:{args.stream}", args.engine_id, "1")
    gate_deadline = time.time() + 600
    while not broker.hget(f"fleet:gate:{args.stream}", "go"):
        if time.time() > gate_deadline:
            raise SystemExit("fleet start gate never opened")
        time.sleep(0.02)
    serving.start()
    agent = None
    exec_before = im.compile_cache_size()
    if args.rollout_dir:
        from analytics_zoo_tpu.serving.rollout import EngineRolloutAgent
        agent = EngineRolloutAgent(
            serving, broker.clone(), stream=args.stream,
            poll_interval_s=0.1, drain_timeout_s=5.0,
            canary_timeout_s=10.0).start()
    stop = []
    signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
    while not stop:
        time.sleep(0.05)
    if agent is not None:
        agent.stop()
    # owned set BEFORE stop(): a clean stop releases every lease, so
    # reading after would always report []
    owned_at_stop = serving.lease_table.owned() \
        if args.partitions > 1 else None
    serving.stop()
    sources = {}
    for v in im.warmup_source.values():
        sources[v] = sources.get(v, 0) + 1
    m = serving.metrics()
    stages = {k: round(v.get("p50_ms", 0.0), 2)
              for k, v in m.get("stages", {}).items()}
    stages["predict"] = round(m["predict"].get("p50_ms", 0.0), 2)
    n_batches = m.get("stages", {}).get("dispatch", {}).get("count", 0)
    report = {"engine_id": args.engine_id,
              "sources": sources,
              "records_served": serving.records_served,
              "stage_p50_ms": stages,
              "avg_read_batch": round(
                  serving.records_read / n_batches, 2)
              if n_batches else None,
              "claimed_records": m.get("claimed_records", 0)}
    if owned_at_stop is not None:
        report["partitions_owned"] = owned_at_stop
    if args.rollout_dir:
        # the 0-compiles-on-swap evidence: executable count after the
        # rollout minus before — a same-structure swap adds nothing
        report["model_version"] = serving.model_version
        report["swap"] = agent.last_swap if agent is not None else None
        report["executables_delta"] = \
            im.compile_cache_size() - exec_before
    print(json.dumps(report))
    return 0


def _fleet_spawn(k, stream, port, cache_dir, claim_min_idle, batch,
                 start_idx=0, extra_args=()):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)       # hermetic CPU children
    procs = []
    for i in range(start_idx, start_idx + k):
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--fleet-child",
             "--broker-url", f"redis://127.0.0.1:{port}",
             "--stream", stream, "--engine-id", f"engine-{i}",
             "--compile-cache-dir", cache_dir,
             "--claim-min-idle", str(claim_min_idle),
             "--fleet-batch", str(batch), "--pin-core", str(i)]
            + list(extra_args),
            env=env, cwd=os.path.dirname(os.path.abspath(__file__)),
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    return procs


def _measure_host_parallelism(seconds: float = 2.0) -> float:
    """Effective parallel speedup this host grants 2 CPU-bound
    processes RIGHT NOW (2.0 = two real cores, ~1.0 = an oversubscribed
    or one-core sandbox). Shared CI hosts swing between the two within
    minutes (measured 1.96x and 0.82x on the same rig the same day),
    and gVisor-style sandboxes accept sched_setaffinity without
    binding — so the fleet curve records the capacity that actually
    backed it instead of trusting os.cpu_count()."""
    code = ("import time,sys\n"
            "w0=time.perf_counter(); x=0\n"
            "while time.perf_counter()-w0 < %f: x+=1\n"
            "print(x)" % seconds)

    def run(k):
        procs = [subprocess.Popen([sys.executable, "-c", code],
                                  stdout=subprocess.PIPE, text=True)
                 for _ in range(k)]
        total = 0
        for p in procs:
            out, _ = p.communicate(timeout=60 + seconds)
            total += int(out)
        return total

    solo = run(1)
    duo = run(2)
    return round(duo / max(solo, 1), 2)


def _fleet_wait_ready(broker, stream, procs, n, timeout_s=300.0):
    """Wait until n engines have warmed and parked at the start gate."""
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        for p in procs:
            if p.poll() is not None:
                _, err = p.communicate()
                raise SystemExit(
                    f"fleet engine died during startup (rc="
                    f"{p.returncode}):\n{err[-2000:]}")
        if broker.hlen(f"fleet:ready:{stream}") >= n:
            return
        time.sleep(0.05)
    raise SystemExit(f"fleet never reached {n} ready engine(s)")


def _fleet_reports(procs, sig=None):
    """Terminate (or leave killed) children and collect their exit
    JSON; a SIGKILLed child reports nothing, by design."""
    import signal as _signal
    reports = []
    for p in procs:
        if p.poll() is None and sig is not False:
            try:
                p.send_signal(sig or _signal.SIGTERM)
            except OSError:
                pass
    for p in procs:
        try:
            out, _err = p.communicate(timeout=60)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _err = p.communicate()
        for line in (out or "").strip().splitlines()[::-1]:
            try:
                reports.append(json.loads(line))
                break
            except ValueError:
                continue
    return reports


def _fleet_main(args) -> int:
    """`--engines N`: the fleet scaling curve. One MiniRedis carries
    the stream; 1 then N engine PROCESSES (forced-host CPU children,
    one device each) drain the same pre-filled backlog; the chaos leg
    re-runs with a mid-drain SIGKILL of one engine and asserts zero
    accepted-record loss through the claim sweep.

    Host-core honesty (the PR 3 caveat): engine processes burn real
    cores, so an M-core box caps fleet scaling at ~M x regardless of N;
    the JSON reports host_cores and efficiency_vs_host_cores so the
    curve is legible on any rig."""
    import shutil
    import signal as _signal
    import tempfile
    import uuid

    from analytics_zoo_tpu.serving.broker import (RedisBroker,
                                                  encode_ndarray)
    from analytics_zoo_tpu.serving.redis_server import MiniRedisServer

    n = max(2, args.engines)
    total = args.total
    batch = 8
    # same (width, iters) as the child engines build — the prefilled
    # records must match the model's input width
    _fn, _W, sample = _md_model(width=256, iters=1024)
    encoded = encode_ndarray(np.asarray(sample))
    cache_dir = tempfile.mkdtemp(prefix="zoo-fleet-cc-")
    srv = MiniRedisServer().start()
    curve = {}
    reports = []
    chaos = {}
    try:
        def prefill(broker, stream, count):
            t0 = time.perf_counter()
            for _ in range(count):
                broker.xadd(stream, {"uri": uuid.uuid4().hex,
                                     "data": {"t": encoded}})
            return time.perf_counter() - t0

        def drained(broker, stream, count, deadline_s=600.0):
            # HLEN, not HGETALL: polling must not re-serialize the whole
            # result hash over RESP each check — at 20 Hz that steals a
            # measurable slice of the engines' (pinned) cores
            result_key = f"result:{stream}"
            deadline = time.time() + deadline_s
            while time.time() < deadline:
                got = broker.hlen(result_key)
                if got >= count:
                    return got
                time.sleep(0.05)
            return broker.hlen(result_key)

        # -- scaling curve: 1 engine, then N, same backlog ----------------
        host_par = {}
        for k in sorted({1, n}):
            stream = f"serving_stream_fleet{k}"
            broker = RedisBroker(srv.host, srv.port)
            # staggered start: engine 0 warms the shared cache alone
            # (the ~1-cold-compile-per-bucket contract), the rest load
            procs = _fleet_spawn(1, stream, srv.port, cache_dir, 30.0,
                                 batch)
            _fleet_wait_ready(broker, stream, procs, 1)
            if k > 1:
                procs += _fleet_spawn(k - 1, stream, srv.port,
                                      cache_dir, 30.0, batch,
                                      start_idx=1)
                _fleet_wait_ready(broker, stream, procs, k)
            # what the host can give 2 concurrent processes RIGHT
            # BEFORE this leg's drain (engines idle at the gate) — a
            # shared host's capacity swings minute to minute, so one
            # probe at bench start would misstate the leg's ceiling
            host_par[str(k)] = _measure_host_parallelism()
            # the WHOLE backlog lands before the gate opens: the timed
            # window measures fleet drain capacity, not the parent's
            # (contended) sequential xadd rate
            prefill(broker, stream, total)
            broker.hset(f"fleet:gate:{stream}", "go", "1")
            t0 = time.perf_counter()
            got = drained(broker, stream, total)
            dt = time.perf_counter() - t0
            rate = got / dt
            # best-of-2 (the multidevice precedent: single drains swing
            # 2-3x with one-sided scheduler noise on shared rigs): a
            # second backlog through the SAME live fleet; its prefill
            # overlaps consumption, but engines idle-block until it
            # starts so the backlog builds far faster than it drains
            t0 = time.perf_counter()
            prefill(broker, stream, total)
            got2 = drained(broker, stream, 2 * total) - total
            dt2 = time.perf_counter() - t0
            rate = max(rate, got2 / dt2)
            curve[str(k)] = round(rate, 1)
            reports += _fleet_reports(procs)
            broker.close()
        host_parallelism = max(host_par.values())

        # -- chaos leg: SIGKILL one of N mid-drain ------------------------
        stream = "serving_stream_fleet_chaos"
        broker = RedisBroker(srv.host, srv.port)
        claim_idle = 1.0
        procs = _fleet_spawn(1, stream, srv.port, cache_dir, claim_idle,
                             batch)
        _fleet_wait_ready(broker, stream, procs, 1)
        procs += _fleet_spawn(n - 1, stream, srv.port, cache_dir,
                              claim_idle, batch, start_idx=1)
        _fleet_wait_ready(broker, stream, procs, n)
        result_key = f"result:{stream}"
        prefill(broker, stream, total)
        broker.hset(f"fleet:gate:{stream}", "go", "1")
        deadline = time.time() + 600
        while broker.hlen(result_key) < total // 3 \
                and time.time() < deadline:
            time.sleep(0.01)
        # SIGKILL: no drain, no deregistration, unacked records strand
        # in the dead engine's PEL until a peer's claim sweep
        procs[0].send_signal(_signal.SIGKILL)
        t_kill = time.perf_counter()
        got = drained(broker, stream, total)
        t_done = time.perf_counter()
        pending_left = broker.pending_count(
            stream, "serving_group")
        chaos = {
            "engines": n,
            "killed": "engine-0",
            "kill_at_fraction": 1 / 3,
            "claim_min_idle_s": claim_idle,
            "record_loss": total - got,
            "zero_loss": got == total,
            "pending_after_drain": pending_left,
            "engine_kill_redelivery_ms": round(
                (t_done - t_kill) * 1e3, 1),
        }
        reports += _fleet_reports(procs)
        broker.close()
    finally:
        srv.stop()
        shutil.rmtree(cache_dir, ignore_errors=True)

    cores = os.cpu_count() or 1
    base = curve.get("1", 0.0)
    speedup = curve.get(str(n), 0.0) / max(base, 1e-9)
    n_buckets = len([b for b in (1, 2, 4, 8) if b <= batch])
    compiled = sum(r.get("sources", {}).get("compiled", 0)
                   for r in reports)
    survivors_claimed = sum(r.get("claimed_records", 0)
                            for r in reports)
    # the ceiling the curve was ACTUALLY measured under: nominal cores,
    # capped by what the host granted 2 concurrent processes at bench
    # time (shared CI hosts swing between ~1x and ~2x within minutes)
    ceiling = min(float(n), float(cores), host_parallelism)
    out = {
        "metric": "serving_fleet_drain",
        "engines": n,
        "total_records": total,
        "batch_size": batch,
        "host_cores": cores,
        "host_effective_parallelism": host_parallelism,
        "host_effective_parallelism_per_leg": host_par,
        "fleet_drain_rps": curve,
        "fleet_speedup": round(speedup, 2),
        "fleet_efficiency": round(speedup / n, 3),
        # engine processes burn real cores: an M-core box caps the
        # fleet at ~M x no matter how many engines run — and a shared
        # box caps it at whatever slice the host is granting right now;
        # a real pod's chips compute off-host and scale with the
        # engine count
        "efficiency_vs_host_cores": round(
            speedup / max(ceiling, 1e-9), 3),
        "note": ("engine compute is single-threaded by construction "
                 "(narrow matmuls; sched_setaffinity is advisory in "
                 "sandboxed CI), so the curve caps near "
                 f"{ceiling:g}x here: min(engines, {cores} host cores, "
                 f"measured {host_parallelism:g}x effective host "
                 "parallelism at bench time); real engines on separate "
                 "hosts scale with the engine count"),
        "fleet_zero_loss": chaos.get("zero_loss"),
        "engine_kill_redelivery_ms": chaos.get(
            "engine_kill_redelivery_ms"),
        "chaos": chaos,
        # the shared-cache contract: every engine after the first warms
        # from disk, so cold compiles per bucket stay ~1 across the
        # whole fleet (3 staggered cold starts here: one per leg)
        "cold_compiles_per_bucket": round(
            compiled / max(n_buckets, 1), 2),
        "survivor_claimed_records": survivors_claimed,
        "engine_reports": reports,
    }
    print(json.dumps(out))
    return 0


# -- request plane: ingest A/B + partition scaling (ISSUE 16) --------------

def _request_plane_main(args) -> int:
    """`--request-plane`: the million-user request-plane benches.

    Leg 1 — wire-speed ingest A/B against one MiniRedis. The wire
    floor is the measured RESP round trip (minimal HGET: request +
    nil reply). Ingest-only: the same burst enqueued per-record (one
    XADD round trip each — the PR 3 frontend pattern) vs
    `enqueue_batch` (ONE pipelined multi-XADD spanning partition
    streams). End-to-end: the burst through `predict_batch` on a
    `pipelined=False` queue (per-record XADD + per-uri HGET polls) vs
    the batched queue (multi-XADD + HMGET sweeps) vs a
    `StreamingSession`, all against the same in-process
    identity-model engine so the A/B isolates the client wire
    pattern, not model compute. The acceptance figure is frontend
    overhead per record OVER the wire floor, which the batched modes
    must cut >= 2x — the batched overhead deliberately does NOT
    subtract its own (amortized, ~rtt/n) wire share, so the ratio is
    conservative.

    Leg 2 — partition scaling: p in (1, 2, 4) partition streams with
    p engine processes each (fleet children under `--partitions p`),
    the same prefilled backlog per leg routed by the SAME crc32 hash
    the engines' lease tables partition by, drain rps per leg.
    Engine compute is single-threaded by construction (the _md_model
    contract), so the curve caps at min(p, host cores, measured host
    parallelism) — reported per the PR 3/10 honest-ceiling
    convention. A short lease ttl (1 s) keeps the fair-share
    rebalance (engines start owning nothing; the first poll grabs up
    to ceil(p/members)) well inside the first drain; best-of-2 then
    measures the balanced steady state."""
    import shutil
    import tempfile
    import uuid

    from analytics_zoo_tpu import init_orca_context
    from analytics_zoo_tpu.serving.broker import (RedisBroker,
                                                  encode_ndarray)
    from analytics_zoo_tpu.serving.client import InputQueue
    from analytics_zoo_tpu.serving.inference_model import InferenceModel
    from analytics_zoo_tpu.serving.partitions import stream_for
    from analytics_zoo_tpu.serving.redis_server import MiniRedisServer
    from analytics_zoo_tpu.serving.server import ClusterServing

    init_orca_context(cluster_mode="local")
    srv = MiniRedisServer().start()
    cache_dir = tempfile.mkdtemp(prefix="zoo-rp-cc-")
    out = {"metric": "serving_request_plane"}
    try:
        broker = RedisBroker(srv.host, srv.port)

        # wire floor: p50 of the smallest useful RESP round trip
        rtts = []
        for _ in range(300):
            t0 = time.perf_counter()
            broker.hget("wire:floor", "f")
            rtts.append((time.perf_counter() - t0) * 1e3)
        wire_rtt = _percentile(rtts, 0.5)

        # -- ingest-only A/B: per-record XADD vs one multi-XADD ----------
        n_ingest = 400
        burst = [np.full((4,), float(i), np.float32)
                 for i in range(n_ingest)]
        q_sync = InputQueue(RedisBroker(srv.host, srv.port),
                            stream="rp_ingest_sync", pipelined=False)
        t0 = time.perf_counter()
        for s in burst:
            q_sync.enqueue(t=s)
        sync_ms = (time.perf_counter() - t0) * 1e3 / n_ingest
        # partitions=4 on purpose: the fused path must hold its win
        # while fanning one burst across 4 partition streams
        q_pipe = InputQueue(RedisBroker(srv.host, srv.port),
                            stream="rp_ingest_pipe", partitions=4)
        t0 = time.perf_counter()
        q_pipe.enqueue_batch(burst)
        pipe_ms = (time.perf_counter() - t0) * 1e3 / n_ingest
        # wire-only sub-leg: the SAME prebuilt records straight at the
        # broker (no client encode), per-record XADD vs chunked
        # multi-XADD — isolates the wire pattern itself. The full
        # client legs above still pay numpy encode per record in BOTH
        # modes, so on a loopback rtt their ratio is encode-bound.
        prebuilt = [("rp_ingest_wire",
                     {"uri": f"w{i}", "data": {"t": "x" * 64}})
                    for i in range(n_ingest)]
        t0 = time.perf_counter()
        for st, rec in prebuilt:
            broker.xadd(st, rec)
        wire_sync_ms = (time.perf_counter() - t0) * 1e3 / n_ingest
        t0 = time.perf_counter()
        for i in range(0, n_ingest, 64):
            broker.xadd_many(prebuilt[i:i + 64])
        wire_pipe_ms = (time.perf_counter() - t0) * 1e3 / n_ingest
        # per-record mode pays >= 1 round trip per record BY
        # CONSTRUCTION — overhead is what it spends beyond that floor;
        # the batched mode's amortized wire share is NOT subtracted
        # (conservative against the claim)
        ingest_over_sync = max(sync_ms - wire_rtt, 0.0)
        ingest_over_pipe = max(pipe_ms, 1e-6)
        wire_over_sync = max(wire_sync_ms - wire_rtt, 0.0)
        wire_over_pipe = max(wire_pipe_ms, 1e-6)
        out["ingest"] = {
            "n": n_ingest,
            "per_record_xadd_ms": round(sync_ms, 3),
            "batched_xadd_many_ms": round(pipe_ms, 3),
            "overhead_over_wire_ms": {
                "per_record": round(ingest_over_sync, 3),
                "batched": round(ingest_over_pipe, 3)},
            "overhead_reduction": round(
                ingest_over_sync / ingest_over_pipe, 2),
            "wire_only": {
                "per_record_xadd_ms": round(wire_sync_ms, 3),
                "batched_xadd_many_ms": round(wire_pipe_ms, 3),
                "overhead_reduction": round(
                    wire_over_sync / wire_over_pipe, 2)},
        }

        # -- end-to-end A/B through an identity engine -------------------
        e2e_stream = "rp_e2e"
        ident = InferenceModel().load_fn(lambda p, x: x, params=())
        ident.warmup(np.zeros((4,), np.float32),
                     buckets=[1, 2, 4, 8, 16, 32, 64])
        serving = ClusterServing(
            ident, broker=RedisBroker(srv.host, srv.port),
            stream=e2e_stream, batch_size=64, batch_timeout_ms=2).start()
        n_e2e = 240
        e2e = {}
        for mode in ("per_record", "batched", "streaming"):
            q = InputQueue(RedisBroker(srv.host, srv.port),
                           stream=e2e_stream,
                           pipelined=(mode != "per_record"))
            t0 = time.perf_counter()
            if mode == "streaming":
                with q.stream_session(max_inflight=64) as sess:
                    for i, x in enumerate(burst[:n_e2e]):
                        sess.submit(x, uri=f"rp-stream-{i}")
                    got = sess.drain(timeout_s=300)
                assert len(got) == n_e2e
            else:
                res = q.predict_batch(burst[:n_e2e], timeout_s=600)
                assert len(res) == n_e2e
            dt = time.perf_counter() - t0
            e2e[mode] = {
                "per_record_ms": round(dt * 1e3 / n_e2e, 3),
                "rps": round(n_e2e / dt, 1)}
            q.broker.close()
        serving.stop()
        # the per-record e2e floor is TWO round trips (XADD + >= 1
        # HGET); again the batched modes' amortized wire share is not
        # subtracted, keeping the reduction ratios conservative
        e2e_over_sync = max(
            e2e["per_record"]["per_record_ms"] - 2 * wire_rtt, 0.0)
        out["e2e"] = {
            "n": n_e2e, "modes": e2e,
            "overhead_over_wire_ms": round(e2e_over_sync, 3),
            "overhead_reduction_batched": round(
                e2e_over_sync / max(e2e["batched"]["per_record_ms"],
                                    1e-6), 2),
            "overhead_reduction_streaming": round(
                e2e_over_sync / max(e2e["streaming"]["per_record_ms"],
                                    1e-6), 2),
        }

        # -- partition scaling: p engines over p partition streams -------
        total = args.total
        batch = 8
        _fn, _W, sample = _md_model(width=256, iters=1024)
        encoded = encode_ndarray(np.asarray(sample))
        curve, host_par, reports = {}, {}, []
        for p in (1, 2, 4):
            stream = f"serving_stream_rp{p}"
            pb = RedisBroker(srv.host, srv.port)
            extra = ("--partitions", str(p),
                     "--partition-lease-ttl", "1.0")
            # staggered start: engine 0 warms the shared cache alone
            procs = _fleet_spawn(1, stream, srv.port, cache_dir, 30.0,
                                 batch, extra_args=extra)
            _fleet_wait_ready(pb, stream, procs, 1)
            if p > 1:
                procs += _fleet_spawn(p - 1, stream, srv.port,
                                      cache_dir, 30.0, batch,
                                      start_idx=1, extra_args=extra)
                _fleet_wait_ready(pb, stream, procs, p)
            # this leg's ACTUAL ceiling, probed while engines idle at
            # the gate (shared hosts swing minute to minute)
            host_par[str(p)] = _measure_host_parallelism()

            def prefill(count):
                # routed by the same crc32 the engines partition by,
                # shipped as chunked multi-XADDs (the leg's producers
                # run at wire speed too)
                entries = []
                for _ in range(count):
                    uri = uuid.uuid4().hex
                    entries.append((stream_for(stream, uri, p),
                                    {"uri": uri,
                                     "data": {"t": encoded}}))
                for i in range(0, len(entries), 64):
                    pb.xadd_many(entries[i:i + 64])

            def drained(count, deadline_s=600.0):
                key = f"result:{stream}"
                deadline = time.time() + deadline_s
                while time.time() < deadline:
                    if pb.hlen(key) >= count:
                        break
                    time.sleep(0.05)
                return pb.hlen(key)

            # whole backlog lands before the gate opens (the _fleet_main
            # discipline: measure drain capacity, not the prefill)
            prefill(total)
            pb.hset(f"fleet:gate:{stream}", "go", "1")
            t0 = time.perf_counter()
            got = drained(total)
            rate = got / (time.perf_counter() - t0)
            # best-of-2: round two runs on the rebalanced, warm fleet
            t0 = time.perf_counter()
            prefill(total)
            got2 = drained(2 * total) - total
            rate = max(rate, got2 / (time.perf_counter() - t0))
            curve[str(p)] = round(rate, 1)
            reports += _fleet_reports(procs)
            pb.close()

        cores = os.cpu_count() or 1
        hp = max(host_par.values())
        speedup = curve["4"] / max(curve["1"], 1e-9)
        ceiling = min(4.0, float(cores), hp)
        owned = {r.get("engine_id"): r.get("partitions_owned")
                 for r in reports if "partitions_owned" in r}
        out.update({
            "wire_rtt_ms": round(wire_rtt, 3),
            "partitions_drain_rps": curve,
            "partition_speedup_1_to_4": round(speedup, 2),
            "host_cores": cores,
            "host_effective_parallelism": hp,
            "host_effective_parallelism_per_leg": host_par,
            "efficiency_vs_host_ceiling": round(
                speedup / max(ceiling, 1e-9), 3),
            "note": ("engine compute is single-threaded by "
                     "construction, so COMPUTE caps the curve near "
                     f"{ceiling:g}x here: min(4 partitions, {cores} "
                     f"host cores, measured {hp:g}x effective host "
                     "parallelism at bench time). A speedup ABOVE "
                     "that ceiling means the 1-partition baseline was "
                     "stream-serialization-bound, not compute-bound: "
                     "one engine on one stream idles in its own "
                     "read/writeback round trips, and partitioning "
                     "recovers that idle time by overlapping "
                     "independent streams. Real engines on separate "
                     "hosts scale with the partition count."),
            "partitions_owned_final": owned or None,
            "engine_reports": reports,
        })
    finally:
        srv.stop()
        shutil.rmtree(cache_dir, ignore_errors=True)
    print(json.dumps(out))
    return 0


# -- chaos-rollout: kill the gateway + one engine mid-rollout (ISSUE 14) ---

def _chaos_rollout_main(args) -> int:
    """`--chaos-rollout`: the zero-downtime lifecycle under fire.

    A 3-engine fleet serves published checkpoint version 1 while an
    open-loop feeder keeps records flowing. The trainer-side publishes
    version 2; the rollout controller starts converging the fleet
    engine-by-engine. Mid-rollout — at least one engine converted,
    at least one not — BOTH the gateway (controller killed without
    cleanup: its directive row stays behind, mid-campaign) and one
    unconverted engine (SIGKILL: no drain, unacked records strand in
    its PEL) die. A fresh controller then restarts, digests the mixed
    fleet from heartbeat rows alone, and must converge the survivors
    to EXACTLY version 2 with zero accepted-record loss (strict
    per-record accounting: every uri the feeder successfully XADDed
    has a result) and zero XLA compiles from the same-structure swaps
    (per-engine executable-count deltas)."""
    import shutil
    import signal as _signal
    import tempfile
    import threading
    import uuid

    from analytics_zoo_tpu.learn.checkpoint import (CheckpointManager,
                                                    write_publish_marker)
    from analytics_zoo_tpu.serving.broker import (RedisBroker,
                                                  encode_ndarray)
    from analytics_zoo_tpu.serving.fleet import FleetTracker
    from analytics_zoo_tpu.serving.redis_server import MiniRedisServer
    from analytics_zoo_tpu.serving.rollout import RolloutController

    n = 3
    batch = 8
    stream = "serving_stream_rollout"
    _fn, W, sample = _md_model(width=256, iters=1024)
    encoded = encode_ndarray(np.asarray(sample))
    model_dir = tempfile.mkdtemp(prefix="zoo-rollout-ckpt-")
    cache_dir = tempfile.mkdtemp(prefix="zoo-rollout-cc-")
    mgr = CheckpointManager(model_dir, keep=10)
    # publish in the dtype the model SERVES (numpy>=2 promotes the
    # generator's /sqrt(width) to f64; jax would canonicalize at load,
    # but the artifact should say what it means)
    W = np.asarray(W, np.float32)
    mgr.save(1, W)
    write_publish_marker(mgr.run_dir, 1)
    srv = MiniRedisServer().start()
    broker = RedisBroker(srv.host, srv.port)
    accepted = []
    feeding = threading.Event()
    feeding.set()

    def feeder():
        # open-loop, modest rate: the point is continuous traffic
        # THROUGH the rollout, not saturation — every uri appended to
        # `accepted` was acknowledged by the broker and must come back
        while feeding.is_set():
            uri = uuid.uuid4().hex
            try:
                broker.xadd(stream, {"uri": uri, "data": {"t": encoded}})
            except Exception:  # noqa: BLE001 — not accepted, not owed
                time.sleep(0.05)
                continue
            accepted.append(uri)
            time.sleep(0.01)

    procs = []
    out = {"metric": "serving_rollout_chaos", "engines": n}
    reports = []
    try:
        procs = _fleet_spawn(1, stream, srv.port, cache_dir, 1.0, batch,
                             extra_args=("--rollout-dir", model_dir))
        _fleet_wait_ready(broker, stream, procs, 1)
        procs += _fleet_spawn(n - 1, stream, srv.port, cache_dir, 1.0,
                              batch, start_idx=1,
                              extra_args=("--rollout-dir", model_dir))
        _fleet_wait_ready(broker, stream, procs, n)
        broker.hset(f"fleet:gate:{stream}", "go", "1")
        feed_thread = threading.Thread(target=feeder, daemon=True)
        feed_thread.start()
        tracker = FleetTracker(broker.clone(), stream, ttl_s=2.0,
                               poll_min_interval_s=0.05)
        controller = RolloutController(
            broker.clone(), stream, model_dir, tracker,
            poll_interval_s=0.2, engine_timeout_s=120.0).start()
        # trainer publishes version 2 (same structure: 1.01x weights)
        mgr.save(2, W * 1.01)
        write_publish_marker(mgr.run_dir, 2)
        t_publish = time.perf_counter()
        # mid-rollout point: >=1 engine on v2, >=1 still on v1
        deadline = time.time() + 300
        victim = None
        while time.time() < deadline:
            versions = tracker.versions() or {}
            on_new = [e for e, v in versions.items() if v == 2]
            on_old = [e for e, v in versions.items() if v != 2]
            if on_new and on_old:
                victim = sorted(on_old)[0]
                break
            time.sleep(0.02)
        if victim is None:
            raise SystemExit("rollout never reached a mid-point "
                             "(no mixed-version window observed)")
        # kill the GATEWAY (no clean stop: the thread is cut loose and
        # its directive row stays behind) and one UNCONVERTED engine
        controller._stop.set()
        idx = int(victim.split("-")[-1])
        procs[idx].send_signal(_signal.SIGKILL)
        t_kill = time.perf_counter()
        # gateway restarts: a FRESH controller must digest the mess
        tracker2 = FleetTracker(broker.clone(), stream, ttl_s=2.0,
                                poll_min_interval_s=0.05)
        controller2 = RolloutController(
            broker.clone(), stream, model_dir, tracker2,
            poll_interval_s=0.2, engine_timeout_s=120.0).start()
        # traffic keeps flowing a while longer, then stops
        time.sleep(2.0)
        feeding.clear()
        feed_thread.join(timeout=10)
        total = len(accepted)
        # convergence: every ALIVE engine on version 2, exactly
        deadline = time.time() + 300
        converged_at = None
        final_versions = {}
        while time.time() < deadline:
            versions = tracker2.versions() or {}
            vals = set(versions.values())
            if len(versions) == n - 1 and vals == {2}:
                converged_at = time.perf_counter()
                final_versions = dict(versions)
                break
            time.sleep(0.05)
        # drain: every accepted record answered (claim sweep owns the
        # dead engine's strays)
        result_key = f"result:{stream}"
        deadline = time.time() + 300
        while broker.hlen(result_key) < total \
                and time.time() < deadline:
            time.sleep(0.05)
        got = broker.hlen(result_key)
        res = broker.hgetall(result_key)
        missing = [u for u in accepted if u not in res]
        controller2.stop()
        status = controller2.status()
        reports = _fleet_reports([p for p in procs
                                  if p.poll() is None])
        # compiles attributable to the SWAPS themselves (the agent
        # measures across its own swap+canary window; the whole-run
        # executables_delta additionally catches unrelated bucket
        # traffic, e.g. a claim sweep forming an unwarmed batch size)
        swap_compiles = sum(
            (r.get("swap") or {}).get("swap_executables_delta") or 0
            for r in reports)
        out.update({
            "total_accepted": total,
            "records_lost": len(missing),
            "zero_loss": not missing,
            "results_written": got,
            "killed_engine": victim,
            "converged": converged_at is not None,
            "convergence_s": round(converged_at - t_publish, 2)
            if converged_at else None,
            "post_kill_convergence_s": round(converged_at - t_kill, 2)
            if converged_at else None,
            "final_versions": sorted(set(final_versions.values())),
            "swap_compiles": swap_compiles,
            "controller_state": status.get("state"),
            "engine_reports": reports,
        })
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        srv.stop()
        shutil.rmtree(cache_dir, ignore_errors=True)
        shutil.rmtree(model_dir, ignore_errors=True)
    print(json.dumps(out))
    return 0


# -- elastic: diurnal + spike replay, static vs autoscaled fleet -----------
# (ISSUE 11)

def _generative_main(args) -> int:
    """Continuous batching A/B (ISSUE 18): the decode engine vs a
    pad-to-max-restart baseline on the SAME executables and the SAME
    seeded Poisson arrival process with a short-skewed output-length
    mix. The baseline is the naive generative server: seat up to
    `slots` waiting prompts, decode the whole batch to its LONGEST
    max_new, only then admit the next batch — every early finisher
    holds its slot idle until the batch's straggler is done, and every
    arrival mid-batch waits for the restart. Reports tokens/sec, TTFT
    and inter-token-latency p50/p99 for both legs, the slot-utilization
    ratio (active-slot-steps over pool-width-steps), and the fresh-XLA-
    compile count on the continuous leg's request path (must be 0: the
    compile funnel is spied after warmup)."""
    import analytics_zoo_tpu.compile_cache.serialization as ccser
    from analytics_zoo_tpu.models.generative import TinyDecoder
    from analytics_zoo_tpu.serving.broker import MemoryBroker
    from analytics_zoo_tpu.serving.client import InputQueue, OutputQueue
    from analytics_zoo_tpu.serving.decode import DecodeServing
    from analytics_zoo_tpu.serving.inference_model import (InferenceModel,
                                                           _next_bucket)

    SLOTS, MAX_KV = 8, 128
    KV_BUCKETS = [16, 32, 64, 128]
    PROMPT_BUCKETS = [8, 16]
    MAX_NEW_CAP = 48
    n = int(os.environ.get("BENCH_GEN_REQUESTS", 64))
    rng = np.random.default_rng(7)
    prompts = [rng.integers(1, 64,
                            size=int(rng.integers(2, 15))).astype(np.int32)
               for _ in range(n)]
    # bimodal output mix — mostly short (geometric, mean ~5) with every
    # 8th request a full-length straggler (the chat + summarization mix
    # of the Orca/vLLM evals): the regime where pad-to-max wastes the
    # most slot-steps, because each straggler pins its whole batch
    max_new = np.minimum(1 + rng.geometric(0.25, n),
                         MAX_NEW_CAP).astype(int)
    max_new[::8] = MAX_NEW_CAP
    # arrival rate sized to SATURATE the slot pool (the regime the A/B
    # is about: under light load both disciplines idle and tie)
    arrivals = np.cumsum(rng.exponential(0.002, n))

    # big enough that step COMPUTE dominates the engine's per-step
    # bookkeeping (broker intake + token-row writes); a 2-layer toy
    # makes the A/B measure engine overhead instead of scheduling
    dec = TinyDecoder(vocab=128, n_layers=4, n_heads=4, head_dim=16,
                      max_len=MAX_KV)
    im = InferenceModel(placement="replicated", num_replicas=1)
    im.load_generative(dec.prefill_fn, dec.step_fn, dec.init_params(0))
    t0 = time.perf_counter()
    im.warmup_generative(dec.init_kv, slots=SLOTS, max_kv_len=MAX_KV,
                         prompt_buckets=PROMPT_BUCKETS,
                         kv_buckets=KV_BUCKETS)
    warmup_s = time.perf_counter() - t0

    # ---- continuous leg: the decode engine over the broker rails ----
    compile_calls = []
    orig_compile = ccser.compile_lowered

    def spy(lowered):
        compile_calls.append(1)
        return orig_compile(lowered)

    ccser.compile_lowered = spy
    broker = MemoryBroker()
    srv = DecodeServing(im, dec.init_kv, broker=broker, slots=SLOTS,
                        max_kv_len=MAX_KV, kv_buckets=KV_BUCKETS,
                        prompt_buckets=PROMPT_BUCKETS,
                        max_new_default=MAX_NEW_CAP).start()
    inq = InputQueue(broker)
    outq = OutputQueue(broker)
    t0 = time.perf_counter()
    uris = []
    for i in range(n):
        dt = t0 + arrivals[i] - time.perf_counter()
        if dt > 0:
            time.sleep(dt)
        uris.append(inq.enqueue(t=prompts[i], max_new=int(max_new[i]),
                                stream=1))
    while srv.stats["finished"] < n:          # serving wall, not
        time.sleep(0.001)                     # post-hoc drain time
        if time.perf_counter() - t0 > 300:
            raise SystemExit("continuous leg stalled")
    cont_wall = time.perf_counter() - t0
    cont_ttft, cont_itl = [], []
    for u in uris:                            # post-hoc stream drain
        ms = [e["ms"] for e in outq.stream_tokens(u, timeout_s=30)
              if not e.get("done")]
        cont_ttft.append(ms[0])
        cont_itl += list(np.diff(ms))
    srv.stop()
    ccser.compile_lowered = orig_compile
    cont = {
        "tokens": srv.stats["tokens"],
        "wall_s": round(cont_wall, 4),
        "tokens_per_s": round(srv.stats["tokens"] / cont_wall, 1),
        "ttft_ms": {"p50": round(_percentile(cont_ttft, 0.5), 3),
                    "p99": round(_percentile(cont_ttft, 0.99), 3)},
        "itl_ms": {"p50": round(_percentile(cont_itl, 0.5), 3),
                   "p99": round(_percentile(cont_itl, 0.99), 3)},
        "slot_utilization": round(srv.utilization(), 4),
        "steps": srv.stats["steps"],
    }

    # ---- baseline leg: pad-to-max-restart on the same executables ----
    kv = dec.init_kv(SLOTS, MAX_KV)
    t0 = time.perf_counter()
    base_ttft, base_itl = [], []
    toks, pos, gen, last = {}, {}, {}, {}
    slot_active = slot_total = steps = tokens = 0
    arrived = finished = 0
    from collections import deque
    waiting: deque = deque()
    while finished < n:
        now = time.perf_counter() - t0
        while arrived < n and arrivals[arrived] <= now:
            waiting.append(arrived)
            arrived += 1
        if not waiting:
            time.sleep(max(0.0, t0 + arrivals[arrived]
                           - time.perf_counter()))
            continue
        batch = [waiting.popleft()
                 for _ in range(min(SLOTS, len(waiting)))]
        for s, idx in enumerate(batch):
            p = prompts[idx]
            pb = _next_bucket(len(p), PROMPT_BUCKETS)
            padded = np.zeros(pb, np.int32)
            padded[:len(p)] = p
            kv, logits = im.generative_prefill(kv, padded, len(p), s)
            toks[idx] = int(np.asarray(logits).argmax())
            tnow = time.perf_counter() - t0
            base_ttft.append((tnow - arrivals[idx]) * 1e3)
            last[idx], gen[idx], pos[idx] = tnow, 1, len(p)
            tokens += 1
        # pad-to-max: the batch decodes until its LONGEST request is
        # done; early finishers keep burning their slot
        for _ in range(max(max_new[idx] for idx in batch) - 1):
            toks_arr = np.zeros(SLOTS, np.int32)
            pos_arr = np.zeros(SLOTS, np.int32)
            for s, idx in enumerate(batch):
                toks_arr[s] = toks[idx]
                pos_arr[s] = pos[idx]
            bucket = _next_bucket(
                max(pos[idx] + 1 for idx in batch), KV_BUCKETS)
            kv, logits = im.generative_step(kv, toks_arr, pos_arr, bucket)
            nxt = np.asarray(logits).argmax(axis=-1)
            tnow = time.perf_counter() - t0
            steps += 1
            slot_total += SLOTS
            slot_active += sum(1 for idx in batch
                               if gen[idx] < max_new[idx])
            for s, idx in enumerate(batch):
                pos[idx] += 1
                if gen[idx] < max_new[idx]:
                    toks[idx] = int(nxt[s])
                    base_itl.append((tnow - last[idx]) * 1e3)
                    last[idx] = tnow
                    gen[idx] += 1
                    tokens += 1
        finished += len(batch)
    base_wall = time.perf_counter() - t0
    base_util = slot_active / slot_total if slot_total else 0.0
    base = {
        "tokens": tokens,
        "wall_s": round(base_wall, 4),
        "tokens_per_s": round(tokens / base_wall, 1),
        "ttft_ms": {"p50": round(_percentile(base_ttft, 0.5), 3),
                    "p99": round(_percentile(base_ttft, 0.99), 3)},
        "itl_ms": {"p50": round(_percentile(base_itl, 0.5), 3),
                   "p99": round(_percentile(base_itl, 0.99), 3)},
        "slot_utilization": round(base_util, 4),
        "steps": steps,
    }

    out = {
        "mode": "generative",
        "backend": jax.default_backend(),
        "n_requests": n, "slots": SLOTS, "max_kv_len": MAX_KV,
        "kv_buckets": KV_BUCKETS, "prompt_buckets": PROMPT_BUCKETS,
        "output_len_mix": {"mean": round(float(max_new.mean()), 2),
                           "max": int(max_new.max()),
                           "cap": MAX_NEW_CAP},
        "warmup_s": round(warmup_s, 3),
        "cold_compiles": len(compile_calls),
        "continuous": cont,
        "baseline_pad_to_max": base,
        "utilization_ratio": round(
            cont["slot_utilization"] / base_util, 2) if base_util else None,
        "tokens_per_s_speedup": round(
            cont["tokens_per_s"] / base["tokens_per_s"], 2),
        "ttft_p99_ratio": round(
            base["ttft_ms"]["p99"] / cont["ttft_ms"]["p99"], 2),
    }
    assert out["cold_compiles"] == 0, \
        "XLA compiled on the decode request path after warmup"
    print(json.dumps(out))
    return 0


def _generative_paged_main(args) -> int:
    """Paged-KV A/B (ISSUE 19) on a prefix-heavy Poisson mix, three
    legs over the SAME model and warmed executables:

    1. capacity — a burst of short shared-prefix prompts through the
       contiguous engine (4 stripes of max_kv_len) and the paged engine
       holding the SAME pool bytes (4*table_len blocks + scratch) but
       4x the lanes: peak concurrent sequences, target >= 2x.
    2. prefix TTFT — cold prompts with distinct 96-token prefixes vs
       prompts re-using them (the cache adopts 6 of 7 chunks copy-
       free): TTFT p50 ratio, target >= 3x.
    3. ITL under a long-prompt join — 4 live streams, then a 104-token
       prompt joins, chunked prefill ON (16-token chunks interleave
       with decode) vs OFF (one monolithic prefill): live streams'
       ITL p99 during the join vs steady state, ON target <= 2x.

    Asserts in-process: zero accepted-record loss (every uri's final
    lands with exactly max_new tokens) and 0 request-path compiles
    across ALL legs (the serialization.compile_lowered funnel is spied
    from the moment warmup ends)."""
    import analytics_zoo_tpu.compile_cache.serialization as ccser
    from analytics_zoo_tpu.models.generative import TinyDecoder
    from analytics_zoo_tpu.serving.broker import MemoryBroker
    from analytics_zoo_tpu.serving.client import InputQueue, OutputQueue
    from analytics_zoo_tpu.serving.decode import DecodeServing
    from analytics_zoo_tpu.serving.inference_model import InferenceModel

    MAX_KV, BL = 128, 16
    KV_BUCKETS = [32, 64, 128]
    TABLE_LEN = MAX_KV // BL
    dec = TinyDecoder(vocab=128, n_layers=4, n_heads=4, head_dim=16,
                      max_len=MAX_KV)
    rng = np.random.default_rng(11)
    warmup_s = 0.0

    def new_im(paged=True):
        im = InferenceModel(placement="replicated", num_replicas=1)
        im.load_generative(
            dec.prefill_fn, dec.step_fn, dec.init_params(0),
            paged_prefill_fn=dec.paged_prefill_fn if paged else None,
            paged_step_fn=dec.paged_step_fn if paged else None)
        return im

    def paged_engine(broker, lanes, kv_blocks, prompt_buckets,
                     prefill_chunk, prefix_cache=True):
        nonlocal warmup_s
        im = new_im()
        chunk_buckets = [b for b in prompt_buckets
                         if prefill_chunk is None or b <= prefill_chunk] \
            or [prompt_buckets[0]]
        t0 = time.perf_counter()
        im.warmup_generative_paged(
            dec.init_kv_blocks, num_blocks=kv_blocks, block_len=BL,
            lanes=lanes, table_len=TABLE_LEN,
            chunk_buckets=chunk_buckets, kv_buckets=KV_BUCKETS)
        warmup_s += time.perf_counter() - t0
        return DecodeServing(
            im, dec.init_kv, broker=broker, slots=lanes,
            max_kv_len=MAX_KV, kv_buckets=KV_BUCKETS,
            prompt_buckets=prompt_buckets, max_new_default=8,
            paged=True, init_kv_blocks=dec.init_kv_blocks,
            block_len=BL, kv_blocks=kv_blocks,
            prefill_chunk=prefill_chunk, prefix_cache=prefix_cache), im

    def drain(srv, outq, uris, expect, wall_cap=300.0):
        t0 = time.perf_counter()
        peak = 0
        while srv.stats["finished"] < expect:
            peak = max(peak, len(srv._active))
            time.sleep(0.001)
            if time.perf_counter() - t0 > wall_cap:
                raise SystemExit("paged leg stalled")
        finals = outq.query_many(uris, deadline=time.monotonic() + 30)
        assert len(finals) == len(uris), \
            f"record loss: {len(uris) - len(finals)} finals missing"
        return peak, finals

    compile_calls = []
    orig_compile = ccser.compile_lowered

    def spy(lowered):
        compile_calls.append(1)
        return orig_compile(lowered)

    # ---- leg 1: capacity at fixed pool bytes --------------------------
    # 24 short prompts (16-token shared prefix + 4-token tail, 8 new),
    # all enqueued at once. Contiguous: 4 stripes of 128 = the whole
    # pool seats 4. Paged: the SAME 512 KV rows = 32 blocks seat every
    # 2-block sequence the 16 lanes can carry.
    CAP_N, STRIPES = 24, 4
    cap_prefix = rng.integers(1, 128, BL).astype(np.int32)
    cap_prompts = [np.concatenate(
        [cap_prefix, rng.integers(1, 128, 4).astype(np.int32)])
        for _ in range(CAP_N)]

    im_c = new_im(paged=False)
    t0 = time.perf_counter()
    im_c.warmup_generative(dec.init_kv, slots=STRIPES, max_kv_len=MAX_KV,
                           prompt_buckets=[32], kv_buckets=KV_BUCKETS)
    warmup_s += time.perf_counter() - t0
    ccser.compile_lowered = spy
    try:
        broker = MemoryBroker()
        srv = DecodeServing(im_c, dec.init_kv, broker=broker,
                            slots=STRIPES, max_kv_len=MAX_KV,
                            kv_buckets=KV_BUCKETS, prompt_buckets=[32],
                            max_new_default=8).start()
        inq, outq = InputQueue(broker), OutputQueue(broker)
        t0 = time.perf_counter()
        uris = [inq.enqueue(t=p, max_new=8) for p in cap_prompts]
        peak_contig, finals = drain(srv, outq, uris, CAP_N)
        contig_wall = time.perf_counter() - t0
        srv.stop()

        broker = MemoryBroker()
        srv, _ = paged_engine(broker, lanes=4 * STRIPES,
                              kv_blocks=STRIPES * TABLE_LEN + 1,
                              prompt_buckets=[16, 32], prefill_chunk=16)
        srv.start()
        inq, outq = InputQueue(broker), OutputQueue(broker)
        t0 = time.perf_counter()
        uris = [inq.enqueue(t=p, max_new=8) for p in cap_prompts]
        peak_paged, finals = drain(srv, outq, uris, CAP_N)
        paged_wall = time.perf_counter() - t0
        cap_hits = srv.stats["prefix_hit_tokens"]
        srv.stop()
        capacity = {
            "pool_kv_rows": STRIPES * MAX_KV,
            "requests": CAP_N,
            "contiguous": {"slots": STRIPES, "peak_concurrent":
                           peak_contig, "wall_s": round(contig_wall, 4)},
            "paged": {"lanes": 4 * STRIPES,
                      "kv_blocks": STRIPES * TABLE_LEN + 1,
                      "peak_concurrent": peak_paged,
                      "wall_s": round(paged_wall, 4),
                      "prefix_hit_tokens": cap_hits},
            "concurrency_ratio": round(peak_paged / peak_contig, 2),
        }

        # ---- leg 2: prefix-hit vs cold TTFT ---------------------------
        # 8 distinct 96-token prefixes, sequentially (each publishes its
        # blocks before the next arrives), then 8 re-users: a hit adopts
        # (104-1)//16 = 6 blocks and prefills ONE 16-token chunk instead
        # of seven.
        PFX_N, PFX_LEN = 8, 6 * BL
        broker = MemoryBroker()
        srv, _ = paged_engine(broker, lanes=8,
                              kv_blocks=8 * TABLE_LEN + 1,
                              prompt_buckets=[16], prefill_chunk=16)
        srv.start()
        inq, outq = InputQueue(broker), OutputQueue(broker)
        prefixes = [rng.integers(1, 128, PFX_LEN).astype(np.int32)
                    for _ in range(PFX_N)]
        ttft = {"cold": [], "hit": []}
        done = 0
        for phase in ("cold", "hit"):
            for pfx in prefixes:
                tail = rng.integers(1, 128, 8).astype(np.int32)
                u = inq.enqueue(t=np.concatenate([pfx, tail]),
                                max_new=4, stream=1)
                while srv.stats["finished"] < done + 1:
                    time.sleep(0.001)
                done += 1
                ms = [e["ms"] for e in
                      outq.stream_tokens(u, timeout_s=30)
                      if not e.get("done")]
                ttft[phase].append(ms[0])
        hit_tokens = srv.stats["prefix_hit_tokens"]
        srv.stop()
        assert hit_tokens >= PFX_N * PFX_LEN, \
            "prefix cache missed re-used prefixes"
        prefix_leg = {
            "prefix_len": PFX_LEN, "prompt_len": PFX_LEN + 8,
            "requests_per_phase": PFX_N,
            "cold_ttft_ms": {
                "p50": round(_percentile(ttft["cold"], 0.5), 3),
                "p99": round(_percentile(ttft["cold"], 0.99), 3)},
            "hit_ttft_ms": {
                "p50": round(_percentile(ttft["hit"], 0.5), 3),
                "p99": round(_percentile(ttft["hit"], 0.99), 3)},
            "prefix_hit_tokens": hit_tokens,
            "ttft_p50_ratio": round(
                _percentile(ttft["cold"], 0.5)
                / _percentile(ttft["hit"], 0.5), 2),
        }

        # ---- leg 3: ITL p99 while a near-max prompt joins -------------
        # 4 live streams decode; a 104-token prompt joins mid-flight.
        # ON: 16-token chunks interleave with decode steps. OFF: one
        # 112-bucket monolithic prefill stalls every stream for its
        # full duration.
        itl_leg = {}
        for chunk in (16, None):
            broker = MemoryBroker()
            srv, _ = paged_engine(broker, lanes=8,
                                  kv_blocks=8 * TABLE_LEN + 1,
                                  prompt_buckets=[16, 112],
                                  prefill_chunk=chunk,
                                  prefix_cache=False)
            srv.start()
            inq, outq = InputQueue(broker), OutputQueue(broker)
            enq_wall = {}
            uris = []
            for _ in range(5):
                p = rng.integers(1, 128, 12).astype(np.int32)
                u = inq.enqueue(t=p, max_new=110, stream=1)
                enq_wall[u] = time.perf_counter()
                uris.append(u)
            while srv.stats["prefills"] < 5:
                time.sleep(0.001)
            # FOUR join events pooled: a single joiner's window holds a
            # handful of ITL samples, so its p99 is the sample max —
            # noise-dominated on a 1-core host
            JOINS = 4
            joiner_uris = []
            for j in range(JOINS):
                time.sleep(0.02)              # steady-state gap
                joiner = rng.integers(1, 128, 104).astype(np.int32)
                ju = inq.enqueue(t=joiner, max_new=4, stream=1)
                enq_wall[ju] = time.perf_counter()
                joiner_uris.append(ju)
                while srv.stats["finished"] < j + 1:
                    time.sleep(0.001)
            peak, finals = drain(srv, outq, uris + joiner_uris,
                                 5 + JOINS)
            windows = []
            for ju in joiner_uris:
                j_ms = [e["ms"] for e in
                        outq.stream_tokens(ju, timeout_s=30)
                        if not e.get("done")]
                windows.append((enq_wall[ju],
                                enq_wall[ju] + j_ms[0] / 1e3))
            steady, during = [], []
            for u in uris:
                ms = [e["ms"] for e in
                      outq.stream_tokens(u, timeout_s=30)
                      if not e.get("done")]
                walls = [enq_wall[u] + m / 1e3 for m in ms]
                for prev, cur in zip(walls, walls[1:]):
                    (during if any(w0 <= cur <= w1 + 0.005
                                   for w0, w1 in windows)
                     else steady).append((cur - prev) * 1e3)
            srv.stop()
            itl_leg["chunked_on" if chunk else "chunked_off"] = {
                "join_events": JOINS,
                "prefill_chunks": srv.stats["prefill_chunks"],
                "steady_itl_ms_p99": round(_percentile(steady, 0.99), 3),
                "join_itl_ms_p99": round(_percentile(during, 0.99), 3),
                "join_over_steady_p99": round(
                    _percentile(during, 0.99)
                    / _percentile(steady, 0.99), 2),
                "join_window_ms": round(sum(
                    (w1 - w0) for w0, w1 in windows) * 1e3 / JOINS, 3),
            }
    finally:
        ccser.compile_lowered = orig_compile

    out = {
        "mode": "generative_paged",
        "backend": jax.default_backend(),
        "max_kv_len": MAX_KV, "block_len": BL,
        "kv_buckets": KV_BUCKETS,
        "warmup_s": round(warmup_s, 3),
        "cold_compiles": len(compile_calls),
        "capacity_fixed_pool_bytes": capacity,
        "prefix_cache_ttft": prefix_leg,
        "long_prompt_join_itl": itl_leg,
    }
    assert out["cold_compiles"] == 0, \
        "XLA compiled on the paged decode request path after warmup"
    print(json.dumps(out))
    return 0


def _generative_chaos_child(args) -> int:
    """One paged decode engine in its own process for the generative
    chaos leg: warm through the SHARED compile cache, park at the
    fleet start gate, then serve with the claim sweep armed. SIGKILL
    is the exercise: no cleanup runs, the PEL keeps this engine's
    unacked generative records, and the surviving peer's sweep adopts
    and RESUMES them from their durable token rows. The compile funnel
    is spied AFTER warmup, so the exit report's `cold_compiles` counts
    request-path compiles only — resume must not add any."""
    import signal

    import analytics_zoo_tpu.compile_cache.serialization as ccser
    from analytics_zoo_tpu import init_orca_context
    from analytics_zoo_tpu.compile_cache import CompileCache
    from analytics_zoo_tpu.models.generative import TinyDecoder
    from analytics_zoo_tpu.serving.broker import connect_broker
    from analytics_zoo_tpu.serving.decode import DecodeServing
    from analytics_zoo_tpu.serving.inference_model import InferenceModel

    init_orca_context(cluster_mode="local")
    dec = TinyDecoder(vocab=64, n_layers=4, n_heads=4, head_dim=16,
                      max_len=64)
    cache = CompileCache(args.compile_cache_dir) \
        if args.compile_cache_dir else None
    im = InferenceModel(placement="replicated", num_replicas=1,
                        compile_cache=cache)
    im.load_generative(dec.prefill_fn, dec.step_fn, dec.init_params(0),
                       paged_prefill_fn=dec.paged_prefill_fn,
                       paged_step_fn=dec.paged_step_fn)
    im.warmup_generative_paged(
        dec.init_kv_blocks, num_blocks=33, block_len=8, lanes=4,
        table_len=8, chunk_buckets=[8, 16], kv_buckets=[16, 32, 64])

    compiles = []
    orig_compile = ccser.compile_lowered

    def spy(lowered):
        compiles.append(1)
        return orig_compile(lowered)

    ccser.compile_lowered = spy
    if args.step_stall_ms > 0:
        # stretch every decode step (the parent sizes this so the
        # SIGKILL reliably lands MID-generation instead of racing a
        # sub-second drain on fast hosts) — the ISSUE-20 stall mode on
        # the decode.step injection point, permanently armed
        from analytics_zoo_tpu.common import faults
        faults.inject("decode.step",
                      faults.Fault(mode="stall",
                                   delay_s=args.step_stall_ms / 1e3))
    broker = connect_broker(args.broker_url)
    srv = DecodeServing(
        im, dec.init_kv, broker=broker, stream=args.stream,
        slots=4, max_kv_len=64, kv_buckets=[16, 32, 64],
        prompt_buckets=[8, 16], max_new_default=40,
        # the queue bound must exceed the whole burst: every prompt
        # must be ACCEPTED (the leg asserts bitwise completion for
        # each), so overload shedding must never fire. The burst still
        # splits between the engines — records land over ~100ms while
        # both loops read every ~step, so neither can hoover the
        # stream in one XREADGROUP
        max_waiting=64,
        engine_id=args.engine_id, paged=True,
        init_kv_blocks=dec.init_kv_blocks, block_len=8, kv_blocks=33,
        claim_min_idle_s=args.claim_min_idle,
        claim_interval_s=max(args.claim_min_idle / 4.0, 0.05),
        heartbeat_interval_s=0.25)
    broker.hset(f"fleet:ready:{args.stream}", args.engine_id, "1")
    gate_deadline = time.time() + 600
    while not broker.hget(f"fleet:gate:{args.stream}", "go"):
        if time.time() > gate_deadline:
            raise SystemExit("chaos start gate never opened")
        time.sleep(0.02)
    srv.start()
    stop = []
    signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
    while not stop:
        time.sleep(0.05)
    srv.stop()
    print(json.dumps({"engine_id": args.engine_id,
                      "cold_compiles": len(compiles),
                      "stats": srv.stats}))
    return 0


def _generative_chaos_main(args) -> int:
    """`--generative --chaos` (ISSUE 20): crash-safe generative
    serving. Two paged decode engines in their own processes drain a
    seeded Poisson prompt mix over one MiniRedis; one engine is
    SIGKILLed mid-generation. The survivor's claim sweep must adopt
    the dead engine's records and resume each from its durable token
    rows, so every completion lands bitwise equal to an uninterrupted
    single-engine oracle on the SAME executables (greedy decode is
    deterministic — zero token loss, zero divergence), a client that
    reconnects mid-stream sees every token index exactly once, and the
    survivor's request path stays at 0 fresh XLA compiles. A second,
    in-process pair then runs the SAME pressure mix with preemption on
    vs off: preemption must complete every sequence under KV-pool
    exhaustion where the disabled leg degrades to answered blocks-full
    truncations — and neither leg may deadlock."""
    import shutil
    import tempfile

    from analytics_zoo_tpu.compile_cache import CompileCache
    from analytics_zoo_tpu.models.generative import TinyDecoder
    from analytics_zoo_tpu.serving.broker import MemoryBroker, RedisBroker
    from analytics_zoo_tpu.serving.client import InputQueue, OutputQueue
    from analytics_zoo_tpu.serving.decode import GROUP, DecodeServing
    from analytics_zoo_tpu.serving.inference_model import InferenceModel
    from analytics_zoo_tpu.serving.redis_server import MiniRedisServer

    LANES, MAX_KV, BL, BLOCKS = 4, 64, 8, 33
    KV_BUCKETS, PROMPT_BUCKETS = [16, 32, 64], [8, 16]
    n = int(os.environ.get("BENCH_GEN_CHAOS_REQUESTS", 48))
    rng = np.random.default_rng(12)
    prompts = [rng.integers(1, 64,
                            size=int(rng.integers(3, 9))).astype(np.int32)
               for _ in range(n)]
    max_new = np.minimum(4 + rng.geometric(0.06, n), 40).astype(int)
    # arrival rate sized to SATURATE both engines (the _generative_main
    # regime): the kill must land while a deep backlog keeps 8 lanes
    # busy, or the dead engine has nothing in flight worth recovering
    arrivals = np.cumsum(rng.exponential(0.002, n))

    cache_dir = args.compile_cache_dir or tempfile.mkdtemp(
        prefix="genchaos-cache-")
    own_cache = args.compile_cache_dir is None
    dec = TinyDecoder(vocab=64, n_layers=4, n_heads=4, head_dim=16,
                      max_len=MAX_KV)
    im = InferenceModel(placement="replicated", num_replicas=1,
                        compile_cache=CompileCache(cache_dir))
    im.load_generative(dec.prefill_fn, dec.step_fn, dec.init_params(0),
                       paged_prefill_fn=dec.paged_prefill_fn,
                       paged_step_fn=dec.paged_step_fn)
    t0 = time.perf_counter()
    # the parent warms FIRST: children then load every executable from
    # the shared cache dir instead of compiling 2x in parallel
    im.warmup_generative_paged(
        dec.init_kv_blocks, num_blocks=BLOCKS, block_len=BL, lanes=LANES,
        table_len=MAX_KV // BL, chunk_buckets=PROMPT_BUCKETS,
        kv_buckets=KV_BUCKETS)
    warmup_s = time.perf_counter() - t0

    def engine(broker, **kw):
        return DecodeServing(
            im, dec.init_kv, broker=broker, slots=LANES,
            max_kv_len=MAX_KV, kv_buckets=KV_BUCKETS,
            prompt_buckets=PROMPT_BUCKETS, max_new_default=40,
            paged=True, init_kv_blocks=dec.init_kv_blocks,
            block_len=BL, kv_blocks=BLOCKS, **kw)

    # ---- uninterrupted oracle: one engine, same executables --------------
    ref_broker = MemoryBroker()
    ref = engine(ref_broker).start()
    rin, rout = InputQueue(ref_broker), OutputQueue(ref_broker)
    ref_uris = [rin.enqueue(t=p, max_new=int(m), stream=1)
                for p, m in zip(prompts, max_new)]
    got = {}
    deadline = time.time() + 240
    while len(got) < n:
        if time.time() > deadline:
            raise SystemExit(f"oracle leg stalled: {len(got)}/{n}")
        got.update(rout.query_many([u for u in ref_uris if u not in got],
                                   delete=True))
        time.sleep(0.005)
    ref.stop()
    expected = [list(np.asarray(got[u]).reshape(-1)) for u in ref_uris]
    total_tokens = sum(len(e) for e in expected)

    # ---- the chaos fleet: 2 engines, kill one mid-generation -------------
    redis_srv = MiniRedisServer().start()
    stream = args.stream
    broker = RedisBroker("127.0.0.1", redis_srv.port)
    result_key = f"result:{stream}"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    procs = [subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--generative-child",
         "--broker-url", f"redis://127.0.0.1:{redis_srv.port}",
         "--stream", stream, "--engine-id", f"engine-{i}",
         "--compile-cache-dir", cache_dir,
         "--claim-min-idle", "0.75", "--step-stall-ms", "8"],
        env=env, cwd=os.path.dirname(os.path.abspath(__file__)),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        for i in range(2)]
    _fleet_wait_ready(broker, stream, procs, 2)
    broker.hset(f"fleet:gate:{stream}", "go", "1")

    def finals_landed(uris):
        return sum(1 for r in broker.hmget(result_key, uris)
                   if r is not None)

    inq, outq = InputQueue(broker), OutputQueue(broker)
    t_start = time.perf_counter()
    uris = []
    for i in range(n):
        dt = t_start + arrivals[i] - time.perf_counter()
        if dt > 0:
            time.sleep(dt)
        uris.append(inq.enqueue(t=prompts[i], max_new=int(max_new[i]),
                                stream=1))

    kill_at = max(2, n // 12)
    deadline = time.time() + 240
    while finals_landed(uris) < kill_at \
            or broker.pending_count(stream, GROUP) < 6:
        if time.time() > deadline:
            raise SystemExit("chaos fleet never reached the kill point")
        if finals_landed(uris) >= n - 2:
            raise SystemExit("load finished before the kill point: "
                             "raise BENCH_GEN_CHAOS_REQUESTS")
        time.sleep(0.002)
    # kill the engine that is ACTIVELY generating (heartbeat token
    # counter grew over one beat window) — killing an idle peer would
    # leave the survivor nothing to recover
    from analytics_zoo_tpu.serving.fleet import engines_key

    def beat_tokens():
        return {eid: json.loads(v).get("tokens", 0)
                for eid, v in broker.hgetall(engines_key(stream)).items()}

    b0 = beat_tokens()
    time.sleep(0.3)
    b1 = beat_tokens()
    target_id = max(b1, key=lambda eid: b1[eid] - b0.get(eid, 0))
    target = int(target_id.rsplit("-", 1)[1])
    pending_at_kill = broker.pending_count(stream, GROUP)
    finals_at_kill = finals_landed(uris)
    assert finals_at_kill < n, "everything finished before the kill"
    t_kill = time.perf_counter()
    procs[target].kill()                          # SIGKILL: no cleanup
    procs[target].wait(timeout=30)
    while finals_landed(uris) < n:
        if time.time() > deadline:
            missing = n - finals_landed(uris)
            raise SystemExit(
                f"token loss: {missing} request(s) never completed "
                f"after the kill")
        time.sleep(0.01)
    recovery_s = time.perf_counter() - t_kill

    # ---- streaming continuity: reconnect replays only missing rows ------
    victim_i = max(i for i in range(n) if max_new[i] >= 8)
    victim = uris[victim_i]
    seen1, seen2, done_ev = [], [], None
    first_conn = outq.stream_tokens(victim, timeout_s=60.0, delete=False)
    for ev in first_conn:                         # "dropped" connection:
        if ev.get("done"):                        # close after 3 frames
            break
        seen1.append(ev)
        if len(seen1) >= 3:
            break
    first_conn.close()
    for ev in outq.stream_tokens(victim, timeout_s=60.0, delete=False,
                                 start=len(seen1)):
        if ev.get("done"):
            done_ev = ev
            break
        seen2.append(ev)
    rows = seen1 + seen2
    assert done_ev is not None and not done_ev.get("error"), done_ev
    assert [ev["i"] for ev in rows] == list(range(len(rows))), \
        "reconnect replayed or skipped a token index"
    assert [ev["t"] for ev in rows] == expected[victim_i], \
        "streamed tokens diverged from the uninterrupted oracle"

    # ---- bitwise parity for every request --------------------------------
    results = {}
    while len(results) < n:
        if time.time() > deadline:
            raise SystemExit("finals landed but would not read back")
        results.update(outq.query_many([u for u in uris
                                        if u not in results], delete=True))
        time.sleep(0.005)
    def _diverge(i, u):
        got = list(np.asarray(results[u]).reshape(-1))
        if got == expected[i]:
            return None
        d = next((j for j, (a, b) in enumerate(zip(got, expected[i]))
                  if a != b), min(len(got), len(expected[i])))
        return (i, len(got), len(expected[i]), d)

    mismatches = [m for m in (_diverge(i, u) for i, u in enumerate(uris))
                  if m is not None]
    assert not mismatches, \
        f"{len(mismatches)} completion(s) diverged from the oracle " \
        f"(idx, got_len, want_len, first_diff): {mismatches[:8]}"

    reports = _fleet_reports(procs)   # SIGTERMs the survivor; the
    assert len(reports) == 1, \
        "expected exactly the survivor's report"   # killed child is silent
    surv = reports[0]["stats"]
    assert reports[0]["cold_compiles"] == 0, \
        "survivor compiled on the resume path"
    assert surv["resumed"] + surv["duplicates"] >= 1, \
        "the kill left no records for the survivor to claim " \
        f"(pending_at_kill={pending_at_kill})"
    redis_srv.stop()

    # ---- preemption vs stall under KV-pool exhaustion --------------------
    # a SMALL pool needs its own warmup (the kv-block buffer's leading
    # dim is baked into the executables); still served from the shared
    # on-disk cache across reruns
    im2 = InferenceModel(placement="replicated", num_replicas=1,
                         compile_cache=CompileCache(cache_dir))
    im2.load_generative(dec.prefill_fn, dec.step_fn, dec.init_params(0),
                        paged_prefill_fn=dec.paged_prefill_fn,
                        paged_step_fn=dec.paged_step_fn)
    im2.warmup_generative_paged(
        dec.init_kv_blocks, num_blocks=13, block_len=BL, lanes=4,
        table_len=4, chunk_buckets=PROMPT_BUCKETS, kv_buckets=[16, 32])
    pressure_prompts = [((np.arange(8) * (i + 3)) % 63 + 1)
                        .astype(np.int32) for i in range(8)]

    def pressure_leg(preempt_max):
        # 8 seqs x 24 new tokens -> 4 blocks each at full context; 4
        # lanes x 4 = 16 demanded vs 12 usable: guaranteed mid-decode
        # exhaustion
        b = MemoryBroker()
        srv = DecodeServing(
            im2, dec.init_kv, broker=b, slots=4, max_kv_len=32,
            kv_buckets=[16, 32], prompt_buckets=PROMPT_BUCKETS,
            max_new_default=24, paged=True,
            init_kv_blocks=dec.init_kv_blocks, block_len=BL,
            kv_blocks=13, preempt_max=preempt_max).start()
        q, o = InputQueue(b), OutputQueue(b)
        t0 = time.perf_counter()
        us = [q.enqueue(t=p, max_new=24, stream=1)
              for p in pressure_prompts]
        gaps, finals = [], {}
        lock = threading.Lock()

        def consume(u):
            last = None
            for ev in o.stream_tokens(u, timeout_s=120.0):
                if ev.get("done"):
                    with lock:
                        finals[u] = ev
                    return
                now = time.perf_counter()
                if last is not None:
                    with lock:
                        gaps.append((now - last) * 1e3)
                last = now

        threads = [threading.Thread(target=consume, args=(u,),
                                    daemon=True) for u in us]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        wall = time.perf_counter() - t0
        srv.stop()
        assert len(finals) == len(us), \
            f"pressure leg (preempt_max={preempt_max}) deadlocked"
        full = sum(1 for ev in finals.values()
                   if ev.get("tokens") is not None
                   and np.asarray(ev["tokens"]).reshape(-1).size == 24)
        return {"preempt_max": preempt_max,
                "itl_ms_p99": round(_percentile(gaps, 0.99), 3),
                "full_completions": full, "requests": len(us),
                "preempted": srv.stats["preempted"],
                "aborted": srv.stats["aborted"],
                "prefix_hit_tokens": srv.stats["prefix_hit_tokens"],
                "wall_s": round(wall, 3)}

    preempt_on = pressure_leg(3)
    preempt_off = pressure_leg(0)
    assert preempt_on["aborted"] == 0 \
        and preempt_on["full_completions"] == len(pressure_prompts), \
        f"preemption failed to complete the pressure mix: {preempt_on}"
    assert preempt_on["preempted"] >= 1, \
        "the pressure mix never actually preempted"

    if own_cache:
        shutil.rmtree(cache_dir, ignore_errors=True)
    out = {
        "mode": "generative_chaos",
        "backend": jax.default_backend(),
        "requests": n, "engines": 2,
        "warmup_s": round(warmup_s, 3),
        "total_tokens": total_tokens,
        "kill": {"finals_at_kill": finals_at_kill,
                 "pending_at_kill": pending_at_kill},
        "recovery": {"all_finals_after_kill_s": round(recovery_s, 3),
                     "resumed": surv["resumed"],
                     "recovered_tokens": surv["recovered_tokens"],
                     "replayed_tokens": surv["replayed_tokens"],
                     "duplicates": surv["duplicates"],
                     "survivor_preempted": surv["preempted"]},
        "survivor_cold_compiles": reports[0]["cold_compiles"],
        "bitwise_identical": n - len(mismatches),
        "token_loss": 0,
        "streaming_reconnect": {
            "first_conn_rows": len(seen1),
            "second_conn_rows": len(seen2),
            "indices_exactly_once": True,
            "bitwise": True},
        "preemption_vs_stall": {"on": preempt_on, "off": preempt_off},
    }
    print(json.dumps(out))
    return 0


def _percentile(samples, q):
    """np.percentile, the same interpolated estimator every other
    p50/p99 in this file uses — a nearest-rank variant here would make
    the elastic replay's p99 a different statistic from the fleet and
    drain benches' in the same JSON round."""
    if not samples:
        return None
    return float(np.percentile(np.asarray(samples), q * 100))


def _elastic_light_ab(srv, cache_dir, batch, n=40):
    """Light-load p50 A/B: one engine at a trickle, adaptive
    deadline-aware dispatch vs the 'static' pad-to-largest-bucket
    strawman. Closed loop (one request in flight — there IS no queue;
    that is the point), sync predict round trips."""
    from analytics_zoo_tpu.serving.broker import RedisBroker
    from analytics_zoo_tpu.serving.client import InputQueue

    out = {}
    for policy in ("static", "adaptive"):
        stream = f"elastic_ab_{policy}"
        broker = RedisBroker(srv.host, srv.port)
        # a FAT straggler window (20 ms) for both engines: the fixed
        # policy always waits it out at light load; adaptive skips it
        # the moment the backlog reads empty
        extra = ["--batch-policy", policy, "--batch-timeout-ms", "20",
                 "--deadline-ms", "30"]
        broker.hset(f"fleet:gate:{stream}", "go", "1")
        procs = _fleet_spawn(1, stream, srv.port, cache_dir, 30.0,
                             batch, extra_args=extra)
        try:
            _fleet_wait_ready(broker, stream, procs, 1)
            q = InputQueue(RedisBroker(srv.host, srv.port), stream)
            _fn, _W, sample = _md_model(width=256, iters=1024)
            arr = np.asarray(sample)
            lats = []
            for i in range(n + 5):
                t0 = time.perf_counter()
                q.predict(arr, timeout_s=30.0)
                dt = (time.perf_counter() - t0) * 1e3
                if i >= 5:                  # settle the cost model
                    lats.append(dt)
                time.sleep(0.02)            # ~3 rps: genuinely light
            out[policy] = {
                "p50_ms": round(_percentile(lats, 0.50), 2),
                "p99_ms": round(_percentile(lats, 0.99), 2),
            }
        finally:
            _fleet_reports(procs)
            broker.close()
    imp = 1.0 - out["adaptive"]["p50_ms"] / max(
        out["static"]["p50_ms"], 1e-9)
    out["p50_improvement_pct"] = round(imp * 100, 1)
    return out


class _EngineLedger:
    """Child engines with spawn/exit timestamps — the chip-seconds
    accounting the static-vs-elastic comparison is about."""

    def __init__(self, stream, port, cache_dir, batch, extra):
        self.stream, self.port = stream, port
        self.cache_dir, self.batch, self.extra = cache_dir, batch, extra
        self.rows = []          # [proc, t_start, t_end|None]
        self.next_idx = 0

    def spawn(self):
        p = _fleet_spawn(1, self.stream, self.port, self.cache_dir,
                         5.0, self.batch, start_idx=self.next_idx,
                         extra_args=self.extra)[0]
        self.next_idx += 1
        self.rows.append([p, time.perf_counter(), None])
        return p

    def retire(self):
        import signal as _signal
        for row in reversed(self.rows):
            if row[2] is None and row[0].poll() is None:
                row[0].send_signal(_signal.SIGTERM)
                return True
        return False

    def reap(self):
        """Stamp exit times for children that have finished draining."""
        for row in self.rows:
            if row[2] is None and row[0].poll() is not None:
                row[2] = time.perf_counter()

    def chip_seconds(self, t_end, t0=None):
        """Engine-seconds in [t0, t_end]: rows spawned before t0 (the
        static fleet's pre-replay cold start, which a production static
        fleet paid long ago) are clamped to the replay window, so the
        static-vs-elastic ratio compares serving commitment, not
        process startup; an elastic MID-run spawn keeps its cold-start
        cost — that lag is part of what elasticity pays."""
        self.reap()
        return sum((row[2] if row[2] is not None else t_end)
                   - (row[1] if t0 is None else max(row[1], t0))
                   for row in self.rows)

    def live_procs(self):
        return [row[0] for row in self.rows if row[0].poll() is None]

    def all_procs(self):
        return [row[0] for row in self.rows]


def _elastic_replay(srv, cache_dir, batch, phases, mode, slo_p99_ms,
                    max_engines):
    """One diurnal+spike replay: an open-loop generator drives the
    phase schedule while a closed-loop prober samples end-to-end
    latency (~8 Hz, tagged by phase — millisecond resolution the
    drain-poll cannot give). `mode` = "static" (max_engines for the
    whole run) or "elastic" (FleetAutoscaler between 1 and
    max_engines)."""
    from analytics_zoo_tpu.serving.broker import RedisBroker, encode_ndarray
    from analytics_zoo_tpu.serving.client import InputQueue
    from analytics_zoo_tpu.serving.fleet import FleetAutoscaler, FleetTracker

    stream = f"elastic_replay_{mode}"
    # what the host grants 2 concurrent processes RIGHT before this
    # leg (the PR 10 per-leg convention): a shared rig's grant swings
    # 1.4-3.4x within minutes, and a spike sized when the host was
    # generous can be unservable by the time this leg runs — the
    # per-leg number makes any SLO miss legible as host starvation
    # vs controller failure
    leg_host_par = _measure_host_parallelism()
    broker = RedisBroker(srv.host, srv.port)
    broker.hset(f"fleet:gate:{stream}", "go", "1")   # no start gate here
    _fn, _W, sample = _md_model(width=256, iters=1024)
    encoded = encode_ndarray(np.asarray(sample))
    arr = np.asarray(sample)
    extra = ["--batch-policy", "adaptive", "--deadline-ms", "150",
             "--batch-timeout-ms", "5",
             "--slo-latency-ms", str(slo_p99_ms)]
    ledger = _EngineLedger(stream, srv.port, cache_dir, batch, extra)
    tracker = scaler = None
    if mode == "static":
        for _ in range(max_engines):
            ledger.spawn()
        _fleet_wait_ready(broker, stream, ledger.all_procs(),
                          max_engines)
    else:
        tracker = FleetTracker(RedisBroker(srv.host, srv.port), stream,
                               ttl_s=1.5)
        # thresholds in RECORDS per alive engine; aggressive up, lazy
        # down — scale-up must beat the spike, scale-down can wait out
        # the tail
        scaler = FleetAutoscaler(
            tracker, RedisBroker(srv.host, srv.port), stream,
            ledger.spawn, ledger.retire,
            min_engines=1, max_engines=max_engines,
            backlog_high=3.0 * batch, backlog_low=1.0 * batch,
            up_stable_s=0.5, down_stable_s=4.0, cooldown_s=3.0,
            # cover the child's cold start (python + jax import +
            # cache-warm ~8s here): without the grace the reconcile
            # clamp re-arms the spawn path mid-startup and every
            # scale-up double-provisions
            spawn_grace_s=45.0,
            interval_s=0.25).start()
        _fleet_wait_ready(broker, stream, ledger.all_procs(), 1)

    samples = []             # (phase, latency_ms)
    stop_probe = threading.Event()

    def prober():
        q = InputQueue(RedisBroker(srv.host, srv.port), stream)
        while not stop_probe.is_set():
            t0 = time.perf_counter()
            try:
                q.predict(arr, timeout_s=30.0)
                samples.append((current_phase[0],
                                (time.perf_counter() - t0) * 1e3))
            except Exception:  # noqa: BLE001 — a lost probe, not a fault
                samples.append((current_phase[0], 30000.0))
            stop_probe.wait(0.12)

    current_phase = ["warm"]
    # two closed-loop probers: during an overload phase one prober's
    # sampling rate collapses to 1/latency — the second keeps the
    # spike-phase sample count meaningful for a p99
    probe_threads = [threading.Thread(target=prober, daemon=True)
                     for _ in range(2)]
    for t in probe_threads:
        t.start()

    gen_broker = RedisBroker(srv.host, srv.port)
    enqueued = 0
    phase_t0 = {}
    engines_seen = {}
    t_run0 = time.perf_counter()
    for name, dur_s, rps in phases:
        current_phase[0] = name
        phase_t0[name] = time.perf_counter()
        period = 1.0 / max(rps, 1e-9)
        t_next = time.perf_counter()
        t_end = phase_t0[name] + dur_s
        while True:
            now = time.perf_counter()
            if now >= t_end:
                break
            if now >= t_next:
                gen_broker.xadd(stream, {"uri": f"{name}-{enqueued}",
                                         "data": {"t": encoded}})
                enqueued += 1
                t_next += period
            else:
                time.sleep(min(t_next - now, 0.005))
            ledger.reap()
        engines_seen[name] = len(ledger.live_procs())
    current_phase[0] = "drain"
    # drain: every open-loop record must land a result (zero loss).
    # hlen is the cheap progress gate, but the authoritative count
    # filters to the generator's own phase-prefixed uris: the probers
    # share this result hash (transient rows between engine HSET and
    # client HDEL, plus a timed-out probe's orphan), and counting
    # theirs could mask a genuinely lost generator record
    result_key = f"result:{stream}"
    phase_names = {name for name, _d, _r in phases}

    def generator_results():
        return sum(1 for u in broker.hgetall(result_key)
                   if u.split("-", 1)[0] in phase_names)

    deadline = time.time() + 300
    while time.time() < deadline:
        ledger.reap()
        if broker.hlen(result_key) >= enqueued \
                and generator_results() >= enqueued:
            break
        time.sleep(0.1)
    t_run_end = time.perf_counter()
    stop_probe.set()
    for t in probe_threads:
        t.join(timeout=35)
    if scaler is not None:
        scaler.stop()
    if tracker is not None:
        tracker.close()
    got = generator_results()
    chip_seconds = ledger.chip_seconds(t_run_end, t0=t_run0)
    reports = _fleet_reports(ledger.all_procs())
    broker.close()

    def phase_stats(name):
        lats = [v for p, v in samples if p == name]
        # steady-state view: the autoscaler's convergence transient
        # (detection + engine cold start) is the first part of the
        # phase; SLO attainment is judged on the settled second half
        # (full-phase numbers are reported alongside)
        k = max(1, int(len(lats) * 0.5))
        steady = lats[k:] if len(lats) > k else lats
        return {
            "n": len(lats),
            "p50_ms": round(_percentile(lats, 0.50), 1) if lats else None,
            "p99_ms": round(_percentile(lats, 0.99), 1) if lats else None,
            "steady_p99_ms": round(_percentile(steady, 0.99), 1)
            if steady else None,
            "engines_at_end": engines_seen.get(name),
        }

    compiled = sum(r.get("sources", {}).get("compiled", 0)
                   for r in reports)
    per_phase = {name: phase_stats(name) for name, _, _ in phases}
    steady = [s["steady_p99_ms"] for s in per_phase.values()
              if s["steady_p99_ms"] is not None]
    return {
        "mode": mode,
        "host_parallelism_at_leg_start": leg_host_par,
        "enqueued": enqueued,
        "results": got,
        "record_loss": enqueued - got,
        "zero_loss": got >= enqueued,
        "chip_seconds": round(chip_seconds, 1),
        "wall_seconds": round(t_run_end - t_run0, 1),
        "engines_spawned": ledger.next_idx,
        "cold_compiled_buckets": compiled,
        "phases": per_phase,
        "slo_p99_ms": slo_p99_ms,
        "slo_held_steady": bool(steady) and all(
            v <= slo_p99_ms for v in steady),
        "engine_reports": reports,
    }


def _elastic_main(args) -> int:
    """`--elastic`: the ISSUE 11 acceptance run. One MiniRedis carries
    everything; a diurnal + spike arrival trace replays twice — against
    a static fleet (max engines, whole run) and against the autoscaled
    elastic fleet — recording per-phase p50/p99, chip-seconds, record
    loss, and cold compiles; plus the light-load adaptive-vs-static-pad
    p50 A/B. Rates are set relative to a measured single-engine
    capacity probe so the spike genuinely overloads one engine on any
    rig. The JSON self-documents the host-parallelism ceiling (PR 3 /
    PR 10 convention): on a shared 2-core box the second engine only
    helps as much as the host actually grants."""
    import shutil
    import tempfile
    import uuid

    from analytics_zoo_tpu.serving.broker import RedisBroker, encode_ndarray
    from analytics_zoo_tpu.serving.redis_server import MiniRedisServer

    batch = 8
    # static baseline = the pre-elastic operating mode: provisioned for
    # peak PLUS one engine of headroom (N+1), up the whole day. The
    # spike needs 2 engines; static runs 3 for the entire replay. The
    # elastic fleet shares the same ceiling and earns its chip-seconds
    # by only using what the backlog demands.
    max_engines = 3
    slo_p99_ms = 1500.0
    cache_dir = tempfile.mkdtemp(prefix="zoo-elastic-cc-")
    srv = MiniRedisServer().start()
    try:
        # -- capacity probe: one adaptive engine drains a backlog ------
        stream = "elastic_cap"
        broker = RedisBroker(srv.host, srv.port)
        broker.hset(f"fleet:gate:{stream}", "go", "1")
        procs = _fleet_spawn(
            1, stream, srv.port, cache_dir, 30.0, batch,
            extra_args=["--batch-policy", "adaptive",
                        "--deadline-ms", "150"])
        _fleet_wait_ready(broker, stream, procs, 1)
        _fn, _W, sample = _md_model(width=256, iters=1024)
        encoded = encode_ndarray(np.asarray(sample))
        n_probe = 240
        t0 = time.perf_counter()
        for i in range(n_probe):
            broker.xadd(stream, {"uri": uuid.uuid4().hex,
                                 "data": {"t": encoded}})
        deadline = time.time() + 120
        while broker.hlen(f"result:{stream}") < n_probe \
                and time.time() < deadline:
            time.sleep(0.05)
        cap_rps = broker.hlen(f"result:{stream}") \
            / (time.perf_counter() - t0)
        _fleet_reports(procs)
        broker.close()

        # -- light-load p50 A/B ----------------------------------------
        light_ab = _elastic_light_ab(srv, cache_dir, batch)

        # host ceiling measured AFTER the probes, right before the
        # replays that the spike sizing has to survive — a probe taken
        # a minute earlier routinely misstates what the replays get
        host_par = _measure_host_parallelism()

        # -- diurnal + spike replay, static then elastic ---------------
        # the diurnal shape: most of the day is light/moderate (one
        # engine's worth), the spike is brief — exactly the regime
        # where static peak-provisioning burns chips doing nothing.
        # The spike must overload ONE engine but stay inside what the
        # scaled-out fleet can absorb on THIS host: on a real pod that
        # is engines x chip, here it is the measured host-parallelism
        # ceiling (a shared 2-core box sometimes grants only ~1.2x —
        # sizing the spike to nominal capacity would then demand the
        # impossible of any autoscaler and measure the rig, not the
        # controller). The factor is recorded in the JSON.
        # 0.7x the granted ceiling: the grant itself swings between the
        # sizing probe and the (later) elastic leg, and a spike sized
        # at the ceiling's edge turns any downswing into an unservable
        # trace — the per-leg host_parallelism_at_leg_start fields make
        # that legible when it still happens
        spike_factor = min(1.25, max(1.05, 0.7 * host_par))
        # the spike must be LONG relative to an engine cold start
        # (~8s nominal, worse when the host is starved): an autoscaler
        # can only show it absorbs a spike that outlives its own
        # scale-up lag — 30s leaves the converged fleet serving most
        # of the phase
        phases = [
            ("light", 15.0, max(3.0, 0.12 * cap_rps)),
            ("ramp", 10.0, 0.45 * cap_rps),
            ("spike", 30.0, spike_factor * cap_rps),
            ("tail", 25.0, 0.12 * cap_rps),
        ]
        static = _elastic_replay(srv, cache_dir, batch, phases,
                                 "static", slo_p99_ms, max_engines)
        elastic = _elastic_replay(srv, cache_dir, batch, phases,
                                  "elastic", slo_p99_ms, max_engines)
    finally:
        srv.stop()
        shutil.rmtree(cache_dir, ignore_errors=True)

    cores = os.cpu_count() or 1
    chip_ratio = elastic["chip_seconds"] / max(static["chip_seconds"],
                                               1e-9)
    out = {
        "metric": "serving_elastic_replay",
        "value": round(chip_ratio, 3),
        "unit": "elastic/static chip-seconds (target <= 0.6)",
        "capacity_probe_rps": round(cap_rps, 1),
        "host_cores": cores,
        "host_effective_parallelism": host_par,
        "phases_rps": {n: round(r, 1) for n, _d, r in phases},
        "spike_factor_vs_one_engine": round(spike_factor, 3),
        "slo_p99_ms": slo_p99_ms,
        "light_load_ab": light_ab,
        "static": static,
        "elastic": elastic,
        "chip_seconds_ratio": round(chip_ratio, 3),
        "elastic_slo_held": elastic["slo_held_steady"],
        "zero_loss": bool(static["zero_loss"] and elastic["zero_loss"]),
        "scale_up_cold_compiles": elastic["cold_compiled_buckets"],
        "note": ("forced-host engines burn real cores: on this "
                 f"{cores}-core rig (measured {host_par:g}x effective "
                 "parallelism at bench time) the second engine only "
                 "adds what the host grants, so spike p99 is bounded "
                 "by the host, not the autoscaler; real engines add a "
                 "whole chip each. Steady p99 excludes each phase's "
                 "first half (the scale-up convergence window)."),
    }
    print(json.dumps(out))
    return 0


# -- cold start: persistent compile cache across process restarts ----------

def _cold_start_child(args) -> int:
    """One server cold-start, timed: build the model, warm every bucket
    through the persistent compile cache, start the engine, serve one
    request end-to-end, report JSON. The parent runs this twice against
    the same cache dir — run 1 compiles and persists, run 2 loads — and
    the warmup wall-time ratio is the cache's cold-start win."""
    from analytics_zoo_tpu import init_orca_context
    from analytics_zoo_tpu.compile_cache import CompileCache
    from analytics_zoo_tpu.serving.broker import MemoryBroker
    from analytics_zoo_tpu.serving.client import InputQueue, OutputQueue
    from analytics_zoo_tpu.serving.inference_model import InferenceModel
    from analytics_zoo_tpu.serving.server import ClusterServing

    init_orca_context(cluster_mode="local")
    model = _serving_model()
    cache = CompileCache(args.compile_cache_dir)
    infer = InferenceModel(compile_cache=cache).load_keras(model)
    t0 = time.perf_counter()
    infer.warmup(np.zeros((32, 32, 3), np.float32),
                 buckets=[1, 2, 4, 8, 16, 32])
    warmup_s = time.perf_counter() - t0
    # prove the warm server actually serves: one request through the
    # full engine
    broker = MemoryBroker()
    serving = ClusterServing(infer, broker=broker, batch_size=8,
                             batch_timeout_ms=2).start()
    uri = InputQueue(broker).enqueue(
        t=np.random.rand(32, 32, 3).astype(np.float32))
    outq = OutputQueue(broker)
    deadline = time.time() + 30
    served = False
    while time.time() < deadline:
        if outq.query(uri, delete=True) is not None:
            served = True
            break
        time.sleep(0.002)
    serving.stop()
    sources = {}
    for v in infer.warmup_source.values():
        sources[v] = sources.get(v, 0) + 1
    print(json.dumps({"warmup_s": round(warmup_s, 4),
                      "served": served,
                      "sources": sources,
                      "cache": cache.stats()}))
    return 0


def _cold_start_main(args) -> int:
    """`--cold-start`: launch the serving child twice against one fresh
    cache dir — cache-cold then cache-warm — and report the warmup
    wall-time ratio (acceptance: warm <= 0.5x cold on the CI rig)."""
    import shutil
    import tempfile

    cache_dir = args.compile_cache_dir or tempfile.mkdtemp(
        prefix="zoo-cc-bench-")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)       # hermetic CPU child
    runs = []
    try:
        for label in ("cold", "warm"):
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--cold-start-child", "--compile-cache-dir", cache_dir],
                env=env, cwd=os.path.dirname(os.path.abspath(__file__)),
                capture_output=True, text=True, timeout=600)
            if proc.returncode != 0:
                sys.stderr.write(proc.stderr)
                raise SystemExit(
                    f"{label} cold-start child failed "
                    f"(rc={proc.returncode})")
            runs.append(json.loads(proc.stdout.strip().splitlines()[-1]))
    finally:
        if args.compile_cache_dir is None:
            shutil.rmtree(cache_dir, ignore_errors=True)
    cold, warm = runs
    ratio = warm["warmup_s"] / max(cold["warmup_s"], 1e-9)
    print(json.dumps({
        "metric": "serving_cold_start_warmup_ratio",
        "value": round(ratio, 3),
        "target": "<=0.5",
        "vs_baseline": round(0.5 / max(ratio, 1e-9), 3),  # >1 beats it
        "cold_warmup_s": cold["warmup_s"],
        "warm_warmup_s": warm["warmup_s"],
        "cold_sources": cold["sources"],
        "warm_sources": warm["sources"],
        "warm_served": warm["served"],
        "cache_entries": warm["cache"]["entries"],
        "cache_bytes": warm["cache"]["bytes"],
    }))
    return 0


def _serving_model():
    from analytics_zoo_tpu.keras import Sequential
    from analytics_zoo_tpu.keras import layers as L
    model = Sequential([
        L.Convolution2D(16, 3, 3, input_shape=(32, 32, 3),
                        border_mode="same", activation="relu"),
        L.MaxPooling2D(),
        L.Convolution2D(32, 3, 3, border_mode="same", activation="relu"),
        L.GlobalAveragePooling2D(),
        L.Dense(10, activation="softmax"),
    ])
    model.ensure_built(np.zeros((1, 32, 32, 3), np.float32))
    return model


def _device_forward_main():
    """BENCH_DEVICE_FORWARD=1: the model's batched forward ON THE TPU,
    tunnel excluded (VERDICT r4 #3). A single dispatch through the dev
    tunnel costs ~100 ms of HTTP round trip that a production v5e host
    (model in-process) never pays, so per-forward device time is measured
    the same way the training bench does: chain k forwards with a data
    dependency inside one jitted fori_loop, read back once, divide by k.
    Percentiles are over repeated trials (sustained-forward latency).
    Also measures the int8-quantized forward (serving/quantization.py)
    for the OpenVINO-int8-parity speedup number."""
    import jax.numpy as jnp

    from analytics_zoo_tpu import init_orca_context
    from analytics_zoo_tpu.serving.quantization import quantize_model_params

    init_orca_context(cluster_mode="local")
    model = _serving_model()
    batch = int(os.environ.get("BENCH_SERVE_BATCH", 32))
    # k sized so per-trial COMPUTE dwarfs the ±10 ms swing of the ~120 ms
    # RTT being subtracted: the tiny CNN runs ~10 µs/forward, so the old
    # k=2000 left ±5 µs of RTT noise on a 10 µs measurement — published
    # p50s went NEGATIVE in noisy windows. 20000 forwards ≈ 0.2 s of
    # compute → ±0.5 µs.
    k, trials = 20000, 10
    x0 = jnp.asarray(np.random.rand(batch, 32, 32, 3).astype(np.float32))

    # dispatch+readback round trip, re-probed ADJACENT to each timed
    # section; subtract the MINIMUM observed (same rationale as the mlp
    # A/B below: percentile/min estimators pick low-RTT draws, so
    # subtracting a stale median over-subtracts)
    @jax.jit
    def empty(x):
        return jnp.sum(x[0, 0, 0])

    def probe_rtt(n=10):
        float(empty(x0))
        vals = []
        for _ in range(n):
            t0 = time.perf_counter()
            float(empty(x0))
            vals.append(time.perf_counter() - t0)
        return vals

    def chained(params):
        @jax.jit
        def run(x):
            def body(_, carry):
                x, acc = carry
                out = model.apply(params, x, training=False)
                # data dependency so XLA cannot elide iterations
                return (x + 1e-12 * jnp.mean(out), acc + jnp.sum(out))
            return jax.lax.fori_loop(0, k, body, (x, 0.0))
        run(x0)[1].block_until_ready()
        float(run(x0)[1])                  # forced readback (warm)
        rtt = min(probe_rtt())
        lat = []
        for _ in range(trials):
            t0 = time.perf_counter()
            float(run(x0)[1])
            lat.append((time.perf_counter() - t0 - rtt) * 1e3 / k)
        if min(lat) <= 0:
            # a congestion spike made the probe exceed a trial's wall
            # time: the data is nonsense — publish null, not 0.0
            return None, None
        # percentiles keep ±(RTT swing)/k ≈ ±0.5 µs of residual noise in
        # p99 (per-trial RTT is unknowable); ~5% on this forward, stated
        # rather than hidden
        lat = np.asarray(sorted(lat))
        return (float(np.percentile(lat, 50)),
                float(np.percentile(lat, 99)))

    rtts = probe_rtt()
    _rtt = float(np.median(rtts))

    f32_params = model.params
    p50, p99 = chained(f32_params)
    q_params = quantize_model_params(model, jax.device_get(f32_params))
    q_params = jax.device_put(q_params)
    p50_q, p99_q = chained(q_params)

    # int8's speedup case is DENSE stacks (the OpenVINO-int8 workload
    # class); the tiny serving CNN above is compute-trivial so its int8
    # delta is noise. Measure a 4096-wide classifier head, f32 vs bf16
    # vs int8. NOTE on regime: inside the chained loop the weights are
    # loop-invariant, so XLA keeps them hot (hoisted conversions /
    # on-chip residency) — this measures STEADY-STATE serving under
    # load (weights resident, activations streaming), where int8's win
    # is the MXU's 2x int8 rate, not weight-fetch bandwidth.
    from analytics_zoo_tpu.keras import Sequential
    from analytics_zoo_tpu.keras import layers as L
    mlp = Sequential([
        L.Dense(4096, activation="relu", input_shape=(4096,)),
        L.Dense(4096, activation="relu"),
        L.Dense(4096, activation="relu"),
        L.Dense(1000, activation="softmax")])
    mlp.ensure_built(np.zeros((1, 4096), np.float32))
    x_mlp = jnp.asarray(np.random.rand(128, 4096).astype(np.float32))

    # k large enough that per-config compute (int8 ≈ 0.09, bf16 ≈ 0.18
    # ms/forward → 0.35-0.7 s per trial) dwarfs the ±10 ms swing of the
    # ~120 ms tunnel RTT being subtracted: at the old k=500 the int8
    # trial was ~45 ms of compute against that swing and the "speedup"
    # field bounced between 1.0x and 12.7x run to run — RTT noise
    k_mlp = 4000

    def make_run(params):
        @jax.jit
        def run(x):
            def body(_, carry):
                x, acc = carry
                out = mlp.apply(params, x, training=False)
                return (x + 1e-12 * jnp.mean(out), acc + jnp.sum(out))
            return jax.lax.fori_loop(0, k_mlp, body, (x, 0.0))
        float(run(x_mlp)[1])                 # warm/compile
        return run

    runs = {
        "f32": make_run(mlp.params),
        "bf16": make_run(jax.tree_util.tree_map(
            lambda a: a.astype(jnp.bfloat16), mlp.params)),
        "int8": make_run(jax.device_put(
            quantize_model_params(mlp, jax.device_get(mlp.params)))),
    }
    # interleaved A/B/C rounds, min-of-N per config: the tunnel chip's
    # minute-scale throughput drift would otherwise bias sequential blocks
    best = {kname: float("inf") for kname in runs}
    for _ in range(6):
        for kname, run in runs.items():
            t0 = time.perf_counter()
            float(run(x_mlp)[1])
            best[kname] = min(best[kname], time.perf_counter() - t0)
    # re-probe the RTT ADJACENT to the A/B loop and subtract the MINIMUM
    # of those FRESH samples only (a stale low-RTT draw from the startup
    # probe would over-subtract): min-of-6 wall times preferentially
    # pick low-RTT draws, so subtracting a median over-subtracts — a
    # constant absolute bias that the fastest config (int8) pays
    # proportionally most, inflating the speedup
    rtt_min = min(probe_rtt())
    mlp_f32, mlp_bf16, mlp_q = (
        (best[kname] - rtt_min) * 1e3 / k_mlp
        for kname in ("f32", "bf16", "int8"))
    # a congested RTT probe larger than a config's wall time would yield
    # nonsense (negative, or astronomically clamped speedups): publish
    # null rather than a number no one should trust
    valid = min(mlp_f32, mlp_bf16, mlp_q) > 0

    rnd = lambda v: None if v is None else round(v, 3)  # noqa: E731
    print(json.dumps({
        "serving_device_forward_p50_ms": rnd(p50),
        "serving_device_forward_p99_ms": rnd(p99),
        "serving_device_forward_int8_p50_ms": rnd(p50_q),
        "serving_device_forward_int8_p99_ms": rnd(p99_q),
        "serving_device_batch": batch,
        "mlp4096_f32_ms": round(mlp_f32, 3) if valid else None,
        "mlp4096_bf16_ms": round(mlp_bf16, 3) if valid else None,
        "mlp4096_int8_ms": round(mlp_q, 3) if valid else None,
        # vs the BEST non-quantized config: with the terminal's
        # --xla_allow_excess_precision the "f32" matmuls already run at
        # bf16 rate and can measure at or under the cast-bearing bf16
        # tree, so bf16-only would flatter int8
        "serving_int8_speedup": (round(min(mlp_f32, mlp_bf16) / mlp_q, 2)
                                 if valid else None),
        "device_dispatch_rtt_ms": round(_rtt * 1e3, 1),
        "device": getattr(jax.devices()[0], "device_kind",
                          str(jax.devices()[0])),
    }))


def _int8_ab_main(args) -> int:
    """--int8-ab (ISSUE 12): int8 vs bf16 vs f32 through the FULL
    serving path — InferenceModel load → per-bucket warmup (AOT/bucket
    machinery identical across precisions) → predict — over the SAME
    bucket set, interleaved rounds so host drift cannot bias one
    precision's block. Reports per-bucket and pooled p50s, the
    int8/bf16 p50 ratio (the ISSUE 12 acceptance is ≤ 0.6 on real
    chips: 2x int8 MXU rate + 4x fewer weight bytes), top-1 parity vs
    f32, and the per-dtype serving_weight_bytes price. On a CPU rig
    XLA has no VNNI-style int8 kernel (the int8 dot lowers to widening
    integer math) so the ratio documents the rig, not the design —
    the JSON self-describes this the way the fleet/scaling benches
    report host-core ceilings."""
    import jax.numpy as jnp

    from analytics_zoo_tpu import init_orca_context
    from analytics_zoo_tpu.keras import Sequential
    from analytics_zoo_tpu.keras import layers as L
    from analytics_zoo_tpu.serving.inference_model import InferenceModel

    init_orca_context(cluster_mode="local")
    width = int(os.environ.get("BENCH_INT8_WIDTH", 1024))
    model = Sequential([
        L.Dense(width, activation="relu", input_shape=(256,)),
        L.Dense(width, activation="relu"),
        L.Dense(width, activation="relu"),
        L.Dense(10, activation="softmax")])
    model.ensure_built(np.zeros((1, 256), np.float32))
    params_f32 = jax.device_get(model.params)
    params_bf16 = jax.tree_util.tree_map(
        lambda a: a.astype(jnp.bfloat16)
        if a.dtype == np.float32 else a, params_f32)

    buckets = [1, 4, 8, 16, 32]

    def load(params=None, quantize=None):
        im = InferenceModel(max_batch=max(buckets))
        if quantize is not None:
            model.params = params_f32
            im.load_keras(model, quantize=quantize)
        elif params is not None:
            im.load_fn(lambda p, x: model.apply(p, x, training=False),
                       params)
        else:
            model.params = params_f32
            im.load_keras(model)
        im.warmup(np.zeros((256,), np.float32), buckets=buckets)
        return im

    variants = {"f32": load(), "bf16": load(params=params_bf16),
                "int8": load(quantize="int8")}
    assert variants["int8"].serving_dtype == "int8"
    assert variants["bf16"].serving_dtype == "bfloat16"

    rs = np.random.RandomState(0)
    xs = {b: rs.rand(b, 256).astype(np.float32) for b in buckets}
    lat = {k: {b: [] for b in buckets} for k in variants}
    rounds, per_round = 6, 8
    for _ in range(rounds):
        for name, im in variants.items():        # interleaved A/B/C
            for b in buckets:
                for _ in range(per_round):
                    t0 = time.perf_counter()
                    im.predict(xs[b])
                    lat[name][b].append(
                        (time.perf_counter() - t0) * 1e3)

    def p50(vals):
        return float(np.percentile(np.asarray(vals), 50))

    pooled = {k: p50(sum(d.values(), [])) for k, d in lat.items()}
    per_bucket = {k: {str(b): round(p50(v), 3)
                      for b, v in d.items()} for k, d in lat.items()}
    # parity on the largest bucket (argmax agreement vs f32)
    xq = rs.rand(256, 256).astype(np.float32)
    pf = np.asarray(variants["f32"].predict(xq))
    p8 = np.asarray(variants["int8"].predict(xq))
    agreement = float((pf.argmax(-1) == p8.argmax(-1)).mean())
    weight_bytes = {k: im.weight_bytes() for k, im in variants.items()}

    ratio = pooled["int8"] / max(pooled["bf16"], 1e-9)
    print(json.dumps({
        "metric": "serving_int8_ab",
        "buckets": buckets,
        "int8_p50_ms": round(pooled["int8"], 3),
        "bf16_p50_ms": round(pooled["bf16"], 3),
        "f32_p50_ms": round(pooled["f32"], 3),
        "int8_vs_bf16_p50_ratio": round(ratio, 3),
        "target_ratio": 0.6,
        "per_bucket_p50_ms": per_bucket,
        "int8_top1_agreement_vs_f32": round(agreement, 4),
        "weight_bytes": weight_bytes,
        "weight_shrink_vs_f32": round(
            weight_bytes["f32"] / max(weight_bytes["int8"], 1), 2),
        "backend": jax.default_backend(),
        "device": getattr(jax.devices()[0], "device_kind",
                          str(jax.devices()[0])),
        "note": ("the ≤0.6 acceptance ratio is an MXU property (2x "
                 "int8 rate + 4x fewer weight bytes); XLA:CPU has no "
                 "VNNI-style int8 kernel, so on a CPU rig this ratio "
                 "documents the rig — read it on real chips, like the "
                 "host-core ceilings of the scaling benches"),
    }))
    return 0


def _registry_tail_metrics():
    """Registry-sourced tail latency + live queue depths for the JSON
    output: the process-wide `MetricsRegistry` accumulated every serving
    instance this bench ran (all broker kinds, pipelined and sync), so
    BENCH_*.json entries carry p50/p95/p99 per stage — not just
    throughput."""
    from analytics_zoo_tpu.observability import get_registry
    snap = get_registry().snapshot()
    latency = {}
    for fam in ("serving_batch_ms", "serving_stage_ms"):
        for s in snap.get(fam, {}).get("series", []):
            key = fam + "".join(f"_{v}" for _, v in
                                sorted(s["labels"].items()))
            latency[key] = {"count": s["count"],
                            "p50_ms": round(s["p50"], 3),
                            "p95_ms": round(s["p95"], 3),
                            "p99_ms": round(s["p99"], 3)}
    depths = {s["labels"]["queue"]: s["value"]
              for s in snap.get("serving_queue_depth", {}).get("series", [])}
    return latency, depths


def _registry_utilization():
    """Live roofline gauges for the bench JSON (ISSUE 6): what fraction
    of the session roofline the serving forwards actually moved —
    per-model HBM-bound fraction and cost-analysis MFU, so BENCH_r06+
    tracks utilization alongside latency with no manual math."""
    from analytics_zoo_tpu.observability import get_accountant
    s = get_accountant().snapshot("serving")
    if not s.get("seconds"):
        return None
    out = {"busy_seconds": round(s["seconds"], 4)}
    for key in ("achieved_tflops", "achieved_hbm_gbps"):
        if s.get(key) is not None:
            out[key] = round(s[key], 4)
    for key in ("mfu", "hbm_utilization"):
        if s.get(key) is not None:
            out[key + "_pct"] = round(s[key] * 100, 3)
    return out


def main():
    from analytics_zoo_tpu import init_orca_context, stop_orca_context
    from analytics_zoo_tpu.serving.inference_model import InferenceModel

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--devices", type=int, default=None,
                    help="multi-device mode: replica-pool/sharded drain "
                         "scaling over N (forced-host) devices")
    ap.add_argument("--total", type=int, default=256,
                    help="backlog size for the multi-device drain")
    ap.add_argument("--chaos", action="store_true",
                    help="chaos mode: replica crash + slow replica + "
                         "broker outage against a live 4-replica engine; "
                         "reports quarantine detection/revival time, "
                         "record loss, and post-recovery throughput")
    ap.add_argument("--cold-start", action="store_true",
                    help="cold-start mode: launch a child server twice "
                         "(cache-cold, cache-warm) against one persistent "
                         "compile cache and report the warmup ratio")
    ap.add_argument("--cold-start-child", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--compile-cache-dir", default=None,
                    help="cache dir for --cold-start / the fleet's "
                         "shared warmup (default: throwaway temp dir)")
    ap.add_argument("--engines", type=int, default=None,
                    help="fleet mode (ISSUE 10): spawn N engine "
                         "processes behind one MiniRedis, report the "
                         "drain scaling curve, and SIGKILL one engine "
                         "mid-drain to prove zero-loss redelivery")
    ap.add_argument("--generative-child", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--step-stall-ms", type=float, default=0.0,
                    help=argparse.SUPPRESS)
    ap.add_argument("--fleet-child", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--broker-url", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--stream", default="serving_stream",
                    help=argparse.SUPPRESS)
    ap.add_argument("--engine-id", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--claim-min-idle", type=float, default=30.0,
                    help=argparse.SUPPRESS)
    ap.add_argument("--chaos-rollout", action="store_true",
                    help="zero-downtime rollout under fire: publish "
                         "v2 to a 3-engine fleet, kill the gateway + "
                         "one engine mid-rollout, restart, assert "
                         "convergence to exactly one version with "
                         "zero accepted-record loss (ISSUE 14)")
    ap.add_argument("--rollout-dir", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--fleet-batch", type=int, default=8,
                    help=argparse.SUPPRESS)
    ap.add_argument("--pin-core", type=int, default=None,
                    help=argparse.SUPPRESS)
    ap.add_argument("--trace-overhead", action="store_true",
                    help="ISSUE 17: drain-throughput A/B at trace "
                         "sampling 0 / 0.01 / 1.0 + trace assembly "
                         "latency")
    ap.add_argument("--int8-ab", action="store_true",
                    help="int8-vs-bf16 A/B through the full serving "
                         "path over one bucket set (ISSUE 12): pooled "
                         "and per-bucket p50s, parity vs f32, per-dtype "
                         "weight bytes")
    ap.add_argument("--elastic", action="store_true",
                    help="diurnal+spike traffic replay: static fleet vs "
                         "autoscaled elastic fleet (adaptive batching, "
                         "tiered admission rails; ISSUE 11)")
    ap.add_argument("--batch-policy", default="adaptive",
                    help=argparse.SUPPRESS)
    ap.add_argument("--deadline-ms", type=float, default=0.0,
                    help=argparse.SUPPRESS)
    ap.add_argument("--batch-timeout-ms", type=int, default=2,
                    help=argparse.SUPPRESS)
    ap.add_argument("--slo-latency-ms", type=float, default=0.0,
                    help=argparse.SUPPRESS)
    ap.add_argument("--request-plane", action="store_true",
                    help="request-plane mode (ISSUE 16): wire-speed "
                         "ingest A/B (per-record XADD vs batched "
                         "multi-XADD vs streaming session, against the "
                         "measured RESP wire floor) plus the "
                         "partition-scaling drain curve at 1/2/4 "
                         "partition streams")
    ap.add_argument("--partitions", type=int, default=1,
                    help=argparse.SUPPRESS)
    ap.add_argument("--partition-lease-ttl", type=float, default=5.0,
                    help=argparse.SUPPRESS)
    ap.add_argument("--generative", action="store_true",
                    help="generative mode (ISSUE 18): continuous-"
                         "batching decode engine vs pad-to-max-restart "
                         "baseline on a seeded Poisson prompt/output "
                         "mix; tokens/sec, TTFT/ITL p99, slot-"
                         "utilization ratio, 0-compile assertion; "
                         "with --chaos (ISSUE 20): SIGKILL one of two "
                         "decode engines mid-generation — bitwise-"
                         "identical resume from durable token rows, "
                         "exactly-once streaming across a reconnect, "
                         "preemption-vs-stall under KV exhaustion")
    ap.add_argument("--paged", action="store_true",
                    help="with --generative (ISSUE 19): paged-KV legs "
                         "on a prefix-heavy Poisson mix — capacity "
                         "multiplier at fixed pool bytes, prefix-hit "
                         "vs cold TTFT, ITL p99 while a near-max "
                         "prompt joins with chunked prefill on vs off, "
                         "zero-loss + 0-compile assertions")
    args = ap.parse_args()
    if args.fleet_child:
        if not (args.broker_url and args.engine_id):
            raise SystemExit("--fleet-child needs --broker-url and "
                             "--engine-id")
        return _fleet_child(args)
    if args.generative_child:
        if not (args.broker_url and args.engine_id):
            raise SystemExit("--generative-child needs --broker-url and "
                             "--engine-id")
        return _generative_chaos_child(args)
    if args.engines:
        return _fleet_main(args)
    if args.request_plane:
        return _request_plane_main(args)
    if args.chaos_rollout:
        return _chaos_rollout_main(args)
    if args.int8_ab:
        return _int8_ab_main(args)
    if args.trace_overhead:
        return _trace_overhead_main(args)
    if args.generative and args.chaos:
        return _generative_chaos_main(args)
    if args.generative and args.paged:
        return _generative_paged_main(args)
    if args.generative:
        return _generative_main(args)
    if args.elastic:
        return _elastic_main(args)
    if args.chaos:
        return _chaos_main(args)
    if args.devices:
        return _multidevice_main(args)
    if args.cold_start_child:
        if not args.compile_cache_dir:
            raise SystemExit("--cold-start-child needs --compile-cache-dir")
        return _cold_start_child(args)
    if args.cold_start:
        return _cold_start_main(args)

    if os.environ.get("BENCH_DEVICE_FORWARD") == "1":
        return _device_forward_main()

    init_orca_context(cluster_mode="local")
    model = _serving_model()
    infer = InferenceModel(concurrent_num=2).load_keras(model)
    # warm every jit bucket the run will hit — warmup() (not bare
    # predicts) so the timer percentiles stay clean AND the roofline
    # layer harvests per-bucket cost analysis for the utilization JSON
    infer.warmup(np.zeros((32, 32, 3), np.float32),
                 buckets=[1, 2, 4, 8, 16, 32])

    results = {}
    for kind in ("memory", "tcp", "redis"):
        p50, p99 = _measure(infer, kind)
        results[kind] = {"p50_ms": round(p50, 2), "p99_ms": round(p99, 2)}

    # sustained concurrent throughput: pipelined engine vs the old
    # synchronous loop, same model, same redis wire path. Interleaved
    # rounds, MEDIAN per engine: single-process thread scheduling swings
    # individual runs up to 3x in both directions (2-core rigs), so a
    # best-of estimator would crown whoever got the lucky spike while
    # sequential blocks would hand one engine the warmed-up half of the
    # session
    # 32 in-flight: shallower closed loops leave the engine unsaturated
    # (the single-process harness, not the server, becomes the limiter
    # and the comparison measures harness scheduling)
    # 5 rounds: with 3, one lucky scheduling spike for either engine
    # still flips the median (observed: sync spiking 186 rps in a round
    # while its other rounds sat at 115-128)
    pipe_rounds, sync_rounds = [], []
    for _ in range(5):
        pipe_rounds.append(_measure_concurrent(infer, "redis",
                                               n_clients=32,
                                               pipelined=True))
        sync_rounds.append(_measure_concurrent(infer, "redis",
                                               n_clients=32,
                                               pipelined=False))
    pipe_rounds.sort(key=lambda r: r[0])
    rps_pipe, cp50, cp99 = pipe_rounds[len(pipe_rounds) // 2]  # median round
    rps_sync = float(np.median([r[0] for r in sync_rounds]))

    # engine-limited drain (stable): pre-filled backlog, no client costs
    drain_pipe = _measure_drain(infer, "redis", pipelined=True)
    drain_sync = _measure_drain(infer, "redis", pipelined=False)

    # decode-share A/B (ISSUE 9): legacy per-record decode vs zero-copy
    # into preallocated bucket buffers, per-stage timers per mode
    decode_ab = _measure_decode_ab(infer)

    # snapshot utilization NOW: the probe/identity models below call
    # load_fn, which resets the "serving" roofline accumulators to
    # describe THEIR program — the JSON must describe the main model's
    serving_utilization = _registry_utilization()

    # no-compile-on-request-path probe (+ cache-hit vs compile counts)
    first_ms, steady_p50, warmup_sources = _warmup_probe(model)

    # pure wire cost: identity model through the redis path, so the
    # composed TPU number (wire + device forward) never counts a model
    # forward twice
    ident = InferenceModel().load_fn(lambda p, x: x, params=())
    wire_p50, wire_p99 = _measure(ident, "redis")
    registry_latency, registry_queue_depth = _registry_tail_metrics()
    stop_orca_context()

    # headline: the Redis-wire path (what BASELINE.md names)
    p50 = results["redis"]["p50_ms"]
    print(json.dumps({
        "metric": "serving_p50_latency",
        "value": p50,
        "unit": "ms",
        "vs_baseline": round(50.0 / max(p50, 1e-6), 3),  # >1 beats target
        "broker": "redis",
        "p99_ms": results["redis"]["p99_ms"],
        "by_broker": results,
        "wire_only_p50_ms": round(wire_p50, 2),
        "wire_only_p99_ms": round(wire_p99, 2),
        "n_requests": N_REQUESTS,
        "serving_concurrent_rps_pipelined": round(rps_pipe, 1),
        "serving_concurrent_rps_sync": round(rps_sync, 1),
        "serving_pipeline_speedup": round(rps_pipe / max(rps_sync, 1e-9),
                                          2),
        "serving_concurrent_p50_ms": round(cp50, 2),
        "serving_concurrent_p99_ms": round(cp99, 2),
        "serving_drain_rps_pipelined": round(drain_pipe, 1),
        "serving_drain_rps_sync": round(drain_sync, 1),
        "serving_drain_speedup": round(drain_pipe / max(drain_sync, 1e-9),
                                       2),
        # host-side decode share: wire p50 vs end-to-end p50 is the
        # budget; the A/B shows what zero-copy decode cut out of it
        "serving_host_gap_p50_ms": round(p50 - wire_p50, 3),
        "serving_decode_ab": decode_ab,
        "serving_warm_first_request_ms": round(first_ms, 3),
        "serving_steady_p50_ms": round(steady_p50, 3),
        # what each probe restart paid: buckets compiled fresh vs
        # warmed from the shared persistent compile cache
        "serving_warmup_compiled_buckets": warmup_sources["compiled"],
        "serving_warmup_cached_buckets": warmup_sources["cached"],
        "registry_latency": registry_latency,
        "registry_queue_depth": registry_queue_depth,
        # roofline gauges (ISSUE 6): cost-analysis MFU + HBM-bound
        # fraction of the serving forwards, vs the session roofline
        "serving_utilization": serving_utilization,
    }))


if __name__ == "__main__":
    sys.exit(main())
