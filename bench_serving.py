"""Serving latency benchmark — p50/p99 end-to-end through the broker.

BASELINE.md target: p50 < 50 ms for the batched TPU InferenceModel behind
the stream queue. Runs the full client → broker → serve loop → client
round trip in-process (the reference measures the same path through Redis,
`docker/cluster-serving/perf/offline-benchmark`). Prints ONE JSON line.

Note on dev rigs with a remote-tunneled TPU (axon): every device call pays
the tunnel's HTTP round trip (~100 ms), which dominates the measurement.
The serving stack itself — client encode, broker, dynamic batching,
bucketed jit dispatch, decode — measures p50 ≈ 0.7 ms with an in-process
backend (`JAX_PLATFORMS=cpu`), far inside the 50 ms target; a real v5e
host runs the model in-process the same way.

    python bench_serving.py
"""

from __future__ import annotations

import json
import sys
import threading
import time

import numpy as np


def main():
    from analytics_zoo_tpu import init_orca_context, stop_orca_context
    from analytics_zoo_tpu.keras import Sequential
    from analytics_zoo_tpu.keras import layers as L
    from analytics_zoo_tpu.serving.broker import MemoryBroker
    from analytics_zoo_tpu.serving.client import InputQueue, OutputQueue
    from analytics_zoo_tpu.serving.inference_model import InferenceModel
    from analytics_zoo_tpu.serving.server import ClusterServing

    init_orca_context(cluster_mode="local")
    model = Sequential([
        L.Convolution2D(16, 3, 3, input_shape=(32, 32, 3),
                        border_mode="same", activation="relu"),
        L.MaxPooling2D(),
        L.Convolution2D(32, 3, 3, border_mode="same", activation="relu"),
        L.GlobalAveragePooling2D(),
        L.Dense(10, activation="softmax"),
    ])
    model.ensure_built(np.zeros((1, 32, 32, 3), np.float32))
    infer = InferenceModel(concurrent_num=2).load_keras(model)
    # warm every jit bucket the run will hit
    for b in (1, 2, 4, 8, 16, 32):
        infer.predict(np.zeros((b, 32, 32, 3), np.float32))

    broker = MemoryBroker()
    serving = ClusterServing(infer, broker=broker, batch_size=32,
                             batch_timeout_ms=2).start()
    inq = InputQueue(broker)
    outq = OutputQueue(broker)

    n = 200
    lat = []
    img = np.random.rand(32, 32, 3).astype(np.float32)
    for i in range(n):
        t0 = time.perf_counter()
        uri = inq.enqueue(t=img)
        while True:
            res = outq.query(uri, delete=True)
            if res is not None:
                break
            time.sleep(0.0005)
        lat.append((time.perf_counter() - t0) * 1e3)
    serving.stop()
    stop_orca_context()

    lat = np.asarray(sorted(lat))
    p50 = float(np.percentile(lat, 50))
    p99 = float(np.percentile(lat, 99))
    print(json.dumps({
        "metric": "serving_p50_latency",
        "value": round(p50, 2),
        "unit": "ms",
        "vs_baseline": round(50.0 / p50, 3),   # >1 = better than target
        "p99_ms": round(p99, 2),
        "n_requests": n,
    }))


if __name__ == "__main__":
    sys.exit(main())
