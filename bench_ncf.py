"""NCF (MovieLens-scale) training throughput through `Estimator.fit` — the
other BASELINE workload (`BASELINE.json` configs[0]; reference
`pyzoo/zoo/models/recommendation/neuralcf.py:30`, `apps/recommendation-ncf`).

NCF is memory-bound, so MFU is the wrong lens (docs/ROOFLINE.md): the
MLP is ~27k matmul params while dense Adam sweeps every embedding-table
parameter (3 reads + 3 writes of p/m/v plus the gradient read = 7
array-wide passes) each step. The JSON therefore reports samples/sec
(the reference community metric) PLUS the roofline-correct utilization:
achieved HBM bytes/s over the chip's peak bandwidth, alongside the
(tiny, expected) MFU. `vs_baseline` compares against a 100k
samples/sec/chip yardstick (no absolute CPU number exists in the
reference tree — BASELINE.md).

Since ISSUE 9 the bench is an A/B: the plain optax sweep is timed
first, then the fused Pallas optimizer kernels
(`fit(fused_optimizer=True)`, the default leg) — `step_ms` /
`step_ms_unfused` / `fused_step_speedup` record the gap, and
`ncf_pct_of_achievable_bound_live` reads the trainer's roofline gauge
for the FUSED program (target ≥60 under BENCH_CALIBRATE=1 on a real
chip). BENCH_FUSED=0 turns leg B back into a second unfused run;
BENCH_LAZY=1 adds the sparse segment path for the tables.

    python bench_ncf.py            # real chip
    BENCH_TINY=1 python bench_ncf.py
"""

from __future__ import annotations

import json
import os
import time

import jax

if ("JAX_DEFAULT_PRNG_IMPL" not in os.environ
        and jax.default_backend() == "tpu"):
    jax.config.update("jax_default_prng_impl", "rbg")

import numpy as np

from analytics_zoo_tpu.utils.roofline import peak_flops, peak_hbm


def main():
    from analytics_zoo_tpu import init_orca_context
    from analytics_zoo_tpu.learn.estimator import Estimator
    from analytics_zoo_tpu.models.recommendation import NeuralCF

    tiny = os.environ.get("BENCH_TINY") == "1"
    if tiny:
        users, items, n, batch, spr = 200, 100, 4096, 512, 4
    else:
        # MovieLens-20M scale: 138k users, 27k items. 4M samples = 512
        # steps/epoch so the one-dispatch-per-epoch device-cached run
        # amortizes the ~0.2s tunnel RTT to <0.5 ms/step (ROOFLINE.md
        # round-5 NCF section); data is device-resident after warmup.
        users, items = 138_000, 27_000
        n = int(os.environ.get("BENCH_N", 1 << 22))
        batch = int(os.environ.get("BENCH_BATCH", 8192))
        spr = int(os.environ.get("BENCH_SPR", 64))

    init_orca_context(cluster_mode="local")
    ncf = NeuralCF(user_count=users, item_count=items, class_num=2,
                   mf_embed=64, user_embed=64, item_embed=64,
                   hidden_layers=(128, 64, 32))
    est = Estimator.from_keras(ncf.model, optimizer="adam",
                               loss="sparse_categorical_crossentropy")

    rs = np.random.RandomState(0)
    x = np.stack([rs.randint(1, users, n), rs.randint(1, items, n)],
                 axis=1).astype(np.int32)
    y = rs.randint(0, 2, n).astype(np.int32)
    # BENCH_LAZY=1 additionally routes the tables through the sparse
    # path. UNFUSED lazy measured SLOWER than dense (XLA set-scatter
    # copies the full table — docs/ROOFLINE.md round-4 note); the FUSED
    # segment kernel (pallas/segment_update.py) removes exactly that
    # copy plus the dense-grad materialization, so lazy is worth
    # re-measuring under BENCH_LAZY=1 BENCH on real chips.
    lazy = os.environ.get("BENCH_LAZY", "0") == "1"
    base_kw = dict(epochs=1, batch_size=batch, steps_per_run=spr,
                   lazy_embeddings=lazy)

    # warmup leg A: pinned unfused — base_kw must not resolve against a
    # fleet-wide ZOO_FUSED_OPT=1, or the timed unfused leg below would
    # pay its full compile inside the measurement
    est.fit((x, y), **base_kw, fused_optimizer=False)

    # BENCH_CALIBRATE=1: measure the session's ACHIEVED bandwidth/MXU
    # rate BEFORE the timed fits and install it as the session roofline
    # (observability/roofline.py) — the live
    # `roofline_hbm_utilization{kind="train"}` gauge the timed fits
    # publish is then %-of-ACHIEVABLE, the same yardstick as the manual
    # pct_of_achievable_bound math below, with no byte model
    achieved_gbps = achieved_tflops = None
    if os.environ.get("BENCH_CALIBRATE") == "1":
        n_params_cal = sum(int(np.prod(np.shape(p))) for p in
                           jax.tree_util.tree_leaves(ncf.model.params))
        achieved_gbps = _calibrate_hbm(n_params_cal)
        achieved_tflops = _calibrate_mxu()
        from analytics_zoo_tpu.observability import set_session_roofline
        set_session_roofline(hbm_gbps=achieved_gbps,
                             tflops=achieved_tflops)

    def timed_fit(estimator, **kw):
        best = float("inf")
        h = None
        for _ in range(1 if tiny else 3):  # best-of-3 (tunnel variance)
            t0 = time.perf_counter()
            h = estimator.fit((x, y), **kw)
            best = min(best, time.perf_counter() - t0)
        return best, h

    # A/B (ISSUE 9): the plain optax sweep first, then the fused Pallas
    # kernels LAST so the live roofline gauges read the fused program.
    # Fresh models per leg: the fused toggle changes the opt-state tree
    # and must not warm-start from the other leg's params.
    dt_unfused, _ = timed_fit(est, **base_kw, fused_optimizer=False)

    ncf = NeuralCF(user_count=users, item_count=items, class_num=2,
                   mf_embed=64, user_embed=64, item_embed=64,
                   hidden_layers=(128, 64, 32))
    est = Estimator.from_keras(ncf.model, optimizer="adam",
                               loss="sparse_categorical_crossentropy")
    fused = os.environ.get("BENCH_FUSED", "1") == "1"
    est.fit((x, y), **base_kw, fused_optimizer=fused)      # warmup leg B
    dt, hist = timed_fit(est, **base_kw, fused_optimizer=fused)
    steps = n // batch
    samples_s = steps * batch / dt
    dev = jax.devices()[0]

    # roofline accounting (docs/ROOFLINE.md):
    params = ncf.model.params
    n_params = sum(int(np.prod(np.shape(p))) for p in
                   jax.tree_util.tree_leaves(params))
    n_emb = sum(int(np.prod(np.shape(p)))
                for k, p in jax.tree_util.tree_leaves_with_path(params)
                if "embed" in str(k).lower())
    n_matmul = n_params - n_emb
    # Adam floor: read grad + read/write each of p, m, v = 7 f32 passes
    # over EVERY parameter per step, PLUS the dense embedding-gradient
    # materialization the round-5 xplane profile showed is a first-class
    # cost (docs/ROOFLINE.md NCF breakdown): a zeros broadcast + a
    # scatter-add output, each a full write pass over every embedding
    # table = 2 more passes over n_emb. Per-sample activation traffic is
    # noise next to either at MovieLens scale. The fused kernels hit
    # this floor by construction (one blocked pass); the unfused optax
    # chain runs 10-12 passes against it — that gap IS the A/B.
    # lazy mode has no dense-sweep byte count worth reporting: the
    # fused segment path touches only batch rows (a different, far
    # smaller floor), the unfused one copies whole tables.
    bytes_step = None if lazy else 4 * (7 * n_params + 2 * n_emb)
    flops_step = 6 * n_matmul * batch
    hbm_util = (None if bytes_step is None
                else (bytes_step * steps / dt) / peak_hbm(dev))
    mfu = (flops_step * steps / dt) / peak_flops(dev)

    # calibration ran pre-fit (so the live gauges saw the session
    # roofline); here only the manual bound comparison remains
    pct_achievable = None
    if achieved_gbps is not None and bytes_step is not None:
        floor_s = bytes_step / (achieved_gbps * 1e9)
        pct_achievable = round(100 * floor_s / (dt / steps), 1)

    # the LIVE version of the same number (ISSUE 6): the trainer's
    # roofline_hbm_utilization{kind="train"} gauge — XLA-counted bytes
    # over the calibrated session roofline, zero manual math. The
    # analytic pct above and this should roughly agree; where they
    # split, XLA's count includes traffic the 7-pass model ignores,
    # and the timing bases differ (the live number covers the LAST
    # timed fit, the manual one the best of 3 — worth ±(tunnel noise)).
    live_pct = live_gbps = None
    try:
        from analytics_zoo_tpu.observability import get_accountant
        live = get_accountant().snapshot("train")
        if live.get("hbm_utilization") is not None:
            live_pct = round(live["hbm_utilization"] * 100, 1)
        if live.get("achieved_hbm_gbps") is not None:
            live_gbps = round(live["achieved_hbm_gbps"], 1)
    except Exception:  # noqa: BLE001 — headline must survive
        pass

    from analytics_zoo_tpu.observability import get_registry
    fused_ms = None
    try:
        fs = get_registry().snapshot().get("training_fused_update_ms")
        if fs and fs.get("series"):
            fused_ms = round(fs["series"][0]["p50"], 3)
    except Exception:  # noqa: BLE001 — headline must survive
        pass

    print(json.dumps({
        "metric": "ncf_train_samples_per_sec_via_estimator_fit",
        "value": round(samples_s, 1),
        "unit": "samples/s",
        "vs_baseline": round(samples_s / 100_000.0, 4),
        "step_ms": round(dt / steps * 1e3, 3),
        "step_ms_unfused": round(dt_unfused / steps * 1e3, 3),
        "fused_optimizer": fused,
        "fused_step_speedup": round(dt_unfused / dt, 3),
        "fused_update_ms": fused_ms,
        "hbm_utilization_pct": (None if hbm_util is None
                                else round(hbm_util * 100, 2)),
        "mfu_pct": round(mfu * 100, 3),
        "bound": ("memory (row-sparse embedding updates)" if lazy
                  else "memory (Adam sweep + dense-grad "
                       "materialization; see docs/ROOFLINE.md NCF "
                       "per-op breakdown)"),
        "lazy_embeddings": lazy,
        "device": getattr(dev, "device_kind", str(dev)),
        # CPU-rig runs: the step-time ratio is a host-core measurement,
        # not a chip one (interpret-mode kernels; see PRs 3/7 caveat)
        "host_cores": (None if jax.default_backend() == "tpu"
                       else os.cpu_count()),
        "achieved_hbm_gbps": achieved_gbps,
        "achieved_mxu_tflops": achieved_tflops,
        "pct_of_achievable_bound": pct_achievable,
        "ncf_pct_of_achievable_bound_live": live_pct,
        "ncf_achieved_hbm_gbps_live": live_gbps,
        "final_loss": float(hist["loss"][-1]),
    }))


def _calibrate_hbm(n_params: int, iters: int = 1000) -> float:
    """Achieved GB/s for a 7-pass (read g,p,m,v; write p,m,v) f32 sweep
    of n_params elements, `iters` iterations in one dispatch. 1000
    iterations ≈ 0.7-2 s of pure sweep, so the ~0.1-0.2 s tunnel RTT in
    the timed window biases the result <15% (100 iters would be ~2x
    biased on a healthy chip)."""
    import jax.numpy as jnp

    g = jnp.full((n_params,), 1e-6, jnp.float32)
    p = jnp.zeros((n_params,), jnp.float32)
    m = jnp.zeros((n_params,), jnp.float32)
    v = jnp.zeros((n_params,), jnp.float32)

    @jax.jit
    def run(p, m, v, g):
        def body(_, carry):
            p, m, v = carry
            m = 0.9 * m + 0.1 * g
            v = 0.999 * v + 0.001 * (g * g)
            p = p - 1e-3 * m / (jnp.sqrt(v) + 1e-8)
            return (p, m, v)
        return jax.lax.fori_loop(0, iters, body, (p, m, v))

    r = run(p, m, v, g)
    float(jnp.sum(r[0]))                      # force completion (warm)
    best = float("inf")
    for _ in range(2):
        t0 = time.perf_counter()
        r = run(p, m, v, g)
        float(jnp.sum(r[0]))
        best = min(best, time.perf_counter() - t0)
    return round(iters * 7 * 4 * n_params / best / 1e9, 1)


def _calibrate_mxu(n: int = 4096, iters: int = 400) -> float:
    """Achieved bf16 TFLOP/s for a chained n×n matmul, `iters` in one
    dispatch (~0.3-0.6 s of pure MXU work). Companion to _calibrate_hbm:
    the tunnel chip's degraded windows measured a HEALTHY bandwidth
    sweep while the same cached BERT step ran 45% slow — whatever
    contends is visible on sustained compute, not short streaming
    bursts, so session health needs both axes."""
    import jax.numpy as jnp

    a = jnp.full((n, n), 0.01, jnp.bfloat16)
    b = jnp.full((n, n), 0.01, jnp.bfloat16)

    @jax.jit
    def run(a, b):
        # y = x.b has entries 0.01*n*x; rescale by exactly that factor so
        # the carry stays ~0.01 (a stronger scale underflows bf16 to zero
        # within ~20 iterations and the sweep times zero matrices)
        inv = jnp.asarray(1.0 / (0.01 * n), jnp.bfloat16)

        def body(_, x):
            return jnp.dot(x, b) * inv
        return jax.lax.fori_loop(0, iters, body, a)

    float(jnp.sum(run(a, b).astype(jnp.float32)))   # warm
    best = float("inf")
    for _ in range(2):
        t0 = time.perf_counter()
        float(jnp.sum(run(a, b).astype(jnp.float32)))
        best = min(best, time.perf_counter() - t0)
    return round(iters * 2 * n**3 / best / 1e12, 1)


if __name__ == "__main__":
    main()
