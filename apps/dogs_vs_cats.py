"""Dogs-vs-cats transfer learning (the reference's `apps/dogs-vs-cats/
transfer-learning.ipynb` scenario, BASELINE config 3) — through the
NNFrames pipeline like the reference notebook: `NNImageReader.read_images`
→ XShards of DataFrames → `NNClassifier` with a chained-ImageProcessing
sample preprocessing → `NNClassifierModel.transform` adds `prediction`
per shard.

A "pretrained" conv trunk is FROZEN by graph surgery (`net.freeze`) so
only the new classifier head trains; then save, reload, and
batch-predict. Synthetic pet photos stand in for the Kaggle download
(texture + hue separate the classes).

    python apps/dogs_vs_cats.py
"""

import os
import tempfile

import numpy as np
import pandas as pd

from analytics_zoo_tpu import init_orca_context
from analytics_zoo_tpu import net as znet
from analytics_zoo_tpu.data import image as I
from analytics_zoo_tpu.keras import Input, Model
from analytics_zoo_tpu.keras import layers as L
from analytics_zoo_tpu.learn.estimator import Estimator
from analytics_zoo_tpu.nnframes import (NNClassifier, NNClassifierModel,
                                        NNImageReader)

SIZE = 32
TRUNK = ("conv1", "conv2")


def make_pet_folder(root, n_per_class=24, seed=0):
    import cv2
    rs = np.random.RandomState(seed)
    for cls, (base, stripe) in (("cats", ((200, 140, 60), 8)),
                                ("dogs", ((90, 120, 190), 16))):
        os.makedirs(os.path.join(root, cls), exist_ok=True)
        for i in range(n_per_class):
            img = np.empty((64, 64, 3), np.uint8)
            img[...] = base
            img[::stripe] = 255 - np.asarray(base, np.uint8)   # fur bands
            img = np.clip(img.astype(np.int32)
                          + rs.randint(0, 25, img.shape), 0,
                          255).astype(np.uint8)
            cv2.imwrite(os.path.join(root, cls, f"{i}.jpg"),
                        cv2.cvtColor(img, cv2.COLOR_RGB2BGR))
    return root


def build_model():
    """Conv trunk (the 'pretrained backbone' role) + fresh 2-way head."""
    inp = Input(shape=(SIZE, SIZE, 3))
    h = L.Convolution2D(8, 3, 3, border_mode="same", activation="relu",
                        name="conv1")(inp)
    h = L.MaxPooling2D()(h)
    h = L.Convolution2D(16, 3, 3, border_mode="same", activation="relu",
                        name="conv2")(h)
    h = L.GlobalAveragePooling2D()(h)
    out = L.Dense(2, activation="softmax", name="head")(h)
    return Model(inp, out)


def main():
    init_orca_context(cluster_mode="local")
    data_dir = make_pet_folder(tempfile.mkdtemp(prefix="pets_"))

    # NNImageReader → XShards of DataFrames (the cluster-wide reference
    # flow; labels are 1-based from the folder layout)
    shards = NNImageReader.read_images(data_dir, with_label=True,
                                       num_shards=4)
    n = sum(len(s) for s in shards.collect())
    print(f"{n} images in {shards.num_partitions()} shards")

    train_aug = (I.ImageColorJitter(brightness_prob=0.3, hue_prob=0.0,
                                    saturation_prob=0.3, contrast_prob=0.3,
                                    seed=1)
                 >> I.ImageRandomCropper(56, 56, mirror=True, seed=2)
                 >> I.ImageResize(SIZE, SIZE)
                 >> I.ImageChannelNormalize(127, 127, 127, 255, 255, 255))
    eval_pre = (I.ImageResize(SIZE, SIZE)
                >> I.ImageChannelNormalize(127, 127, 127, 255, 255, 255))

    import jax
    model = build_model()
    model.ensure_built(np.zeros((1, SIZE, SIZE, 3), np.float32),
                       jax.random.PRNGKey(42))  # "downloaded" weights
    tuned = znet.freeze(model, TRUNK)           # trunk out of grad path
    clf = (NNClassifier(tuned)
           .set_features_col("image").set_label_col("label")
           .set_batch_size(8).set_max_epoch(25)
           .set_sample_preprocessing(train_aug))
    nn_model = clf.fit(shards)                  # sharded Estimator path
    assert not set(tuned.params) & set(TRUNK), "trunk must stay frozen"

    nn_model.set_sample_preprocessing(eval_pre)  # deterministic eval
    scored = nn_model.transform(shards)          # XShards + prediction col
    df = pd.concat(scored.collect(), ignore_index=True)
    acc = float((df["prediction"] == df["label"]).mean())
    print(f"train accuracy {acc:.3f} (only the head trained)")
    assert acc > 0.85, "transfer learning failed to separate the classes"

    path = os.path.join(tempfile.mkdtemp(), "pets_model")
    Estimator.from_keras(tuned).save(path)
    # rebuild with the same "pretrained" trunk, then load the tuned head
    base2 = build_model()
    base2.ensure_built(np.zeros((1, SIZE, SIZE, 3), np.float32),
                       jax.random.PRNGKey(42))
    reloaded = znet.freeze(base2, TRUNK)
    reloaded.compile(optimizer="adam",
                     loss="sparse_categorical_crossentropy")
    Estimator.from_keras(reloaded).load(path)
    re_scored = (NNClassifierModel(reloaded, "image",
                                   zero_based_label=False)
                 .set_sample_preprocessing(eval_pre).transform(shards))
    re_df = pd.concat(re_scored.collect(), ignore_index=True)
    assert bool((re_df["prediction"] == df["prediction"]).all())
    # weight-level check, not just argmax: logits must match numerically
    x_eval = np.stack([np.asarray(eval_pre(im), np.float32)
                       for im in pd.concat(shards.collect())["image"][:8]])
    agree = np.allclose(reloaded.predict(x_eval), tuned.predict(x_eval),
                        atol=1e-5)
    print(f"reloaded model agrees: {agree}")
    assert agree
    print("OK")


if __name__ == "__main__":
    main()
