"""Image augmentation — the data/image pipeline (the reference's
`apps/image-augmentation` notebook scenario).

Build a ChainedPreprocessing of resize / random crop / horizontal flip /
brightness / channel-normalize, run it over an ImageSet, and feed the
augmented set into one training epoch.

    python apps/image_augmentation.py
"""

import numpy as np

from analytics_zoo_tpu import init_orca_context
from analytics_zoo_tpu.data.image import (ChainedPreprocessing,
                                          ImageBrightness,
                                          ImageChannelNormalize,
                                          ImageHFlip, ImageRandomCrop,
                                          ImageResize, ImageSet)
from analytics_zoo_tpu.keras import Sequential
from analytics_zoo_tpu.keras import layers as L


def main():
    init_orca_context(cluster_mode="local")
    rs = np.random.RandomState(0)
    raw = [rs.randint(0, 255, size=(40 + rs.randint(20),
                                    40 + rs.randint(20), 3),
                      ).astype(np.uint8) for _ in range(64)]
    labels = rs.randint(0, 2, size=64).astype(np.int32)
    iset = ImageSet(raw, labels)

    pipeline = ChainedPreprocessing([
        ImageResize(36, 36),
        ImageRandomCrop(32, 32, seed=1),
        ImageHFlip(p=0.5, seed=2),
        ImageBrightness(-16, 16, seed=3),
        ImageChannelNormalize(127.5, 127.5, 127.5, 127.5, 127.5, 127.5),
    ])
    aug = iset.transform(pipeline)
    batch = np.stack(aug.images)
    print(f"augmented: {batch.shape}, value range "
          f"[{batch.min():.2f}, {batch.max():.2f}]")
    assert batch.shape == (64, 32, 32, 3)
    assert -2.0 < batch.min() and batch.max() < 2.0

    model = Sequential([
        L.Convolution2D(4, 3, 3, input_shape=(32, 32, 3),
                        activation="relu", border_mode="same"),
        L.MaxPooling2D(),
        L.Flatten(),
        L.Dense(2, activation="softmax"),
    ])
    model.compile("adam", "sparse_categorical_crossentropy")
    hist = model.fit(batch, labels, batch_size=32, nb_epoch=2)
    print("loss:", [round(v, 3) for v in hist["loss"]])
    print("image augmentation app OK")


if __name__ == "__main__":
    main()
