"""Fraud detection — imbalanced binary classification (the reference's
`apps/fraud-detection` notebook scenario).

Synthetic card-transaction features with a ~2% fraud rate: train a dense
classifier with a class-weighted binary cross-entropy (the imbalance
treatment), evaluate AUC, and pick an operating threshold from
precision/recall on a validation split.

    python apps/fraud_detection.py
"""

import numpy as np

from analytics_zoo_tpu import init_orca_context
from analytics_zoo_tpu.keras import Sequential
from analytics_zoo_tpu.keras import layers as L
from analytics_zoo_tpu.learn.estimator import Estimator
from analytics_zoo_tpu.ops import metrics as zmetrics

FRAUD_RATE = 0.02
N = 4096
DIM = 16


def make_transactions(n=N, seed=0):
    rs = np.random.RandomState(seed)
    y = (rs.rand(n) < FRAUD_RATE).astype(np.float32)
    x = rs.randn(n, DIM).astype(np.float32)
    # fraud shifts a few feature dimensions
    x[y == 1, :4] += 1.5
    x[y == 1, 4:8] *= 1.8
    return x, y[:, None]


def weighted_bce(pos_weight: float):
    def loss(y_true, y_pred):
        import jax.numpy as jnp
        eps = 1e-7
        p = jnp.clip(y_pred, eps, 1 - eps)
        return -jnp.mean(pos_weight * y_true * jnp.log(p)
                         + (1 - y_true) * jnp.log1p(-p))
    return loss


def main():
    init_orca_context(cluster_mode="local")
    x, y = make_transactions()
    split = int(0.8 * len(x))
    (xt, yt), (xv, yv) = (x[:split], y[:split]), (x[split:], y[split:])
    pos_weight = float((1 - yt.mean()) / max(yt.mean(), 1e-6))
    print(f"{int(yt.sum())} fraud / {len(yt)} transactions "
          f"(pos_weight {pos_weight:.1f})")

    model = Sequential([
        L.Dense(32, input_shape=(DIM,), activation="relu"),
        L.Dropout(0.2),
        L.Dense(16, activation="relu"),
        L.Dense(1, activation="sigmoid"),
    ])
    est = Estimator.from_keras(model, optimizer="adam",
                               loss=weighted_bce(pos_weight))
    est.fit((xt, yt), epochs=8, batch_size=256)

    scores = np.asarray(est.predict(xv)).ravel()
    auc_metric = zmetrics.get("auc")
    state = auc_metric.update(auc_metric.init(), yv.ravel(), scores)
    auc_value = float(auc_metric.compute(state))
    print(f"validation AUC: {auc_value:.3f}")

    # threshold sweep: recall at high precision is what fraud ops want
    best = None
    for t in np.linspace(0.1, 0.9, 17):
        pred = scores >= t
        tp = float((pred & (yv.ravel() == 1)).sum())
        prec = tp / max(pred.sum(), 1)
        rec = tp / max(yv.sum(), 1)
        if prec >= 0.5 and (best is None or rec > best[2]):
            best = (t, prec, rec)
    if best:
        print(f"operating point: threshold {best[0]:.2f} -> "
              f"precision {best[1]:.2f}, recall {best[2]:.2f}")
    assert auc_value > 0.8
    print("fraud detection app OK")


if __name__ == "__main__":
    main()
