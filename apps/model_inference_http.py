"""Model inference over plain HTTP (the reference's
`apps/model-inference-examples` family: non-Python callers reach the
serving stack through the HTTP frontend, as the SpringBoot/Flink
examples do through `AbstractInferenceModel`).

Flow: start the in-package RESP2 stream server → the serving loop with a
batched InferenceModel → the HTTP frontend — then act as a FOREIGN
client: plain `urllib` POST /predict with a JSON tensor (no framework
imports on the client side), read predictions and the /metrics
percentiles back.

    python apps/model_inference_http.py
"""

import json
import time
import urllib.request

import numpy as np

from analytics_zoo_tpu import init_orca_context
from analytics_zoo_tpu.keras import Sequential
from analytics_zoo_tpu.keras import layers as L
from analytics_zoo_tpu.serving import (ClusterServing, FrontEnd,
                                       InferenceModel, MiniRedisServer,
                                       RedisBroker)

DIM, CLASSES = 8, 3


def main():
    init_orca_context(cluster_mode="local")
    model = Sequential([
        L.Dense(16, input_shape=(DIM,), activation="relu"),
        L.Dense(CLASSES, activation="softmax"),
    ])
    model.ensure_built(np.zeros((1, DIM), np.float32))
    infer = InferenceModel(concurrent_num=2).load_keras(model)

    redis = MiniRedisServer().start()
    broker = RedisBroker(redis.host, redis.port)
    serving = ClusterServing(infer, broker=broker, batch_size=16,
                             batch_timeout_ms=5).start()
    frontend = FrontEnd(RedisBroker(redis.host, redis.port),
                        serving=serving, port=0).start()
    base = f"http://127.0.0.1:{frontend.port}"
    print(f"stack up: redis={redis.url} frontend={base}")

    try:
        # a foreign client: nothing but stdlib HTTP + JSON
        payload = json.dumps({
            "instances": np.random.rand(4, DIM).round(4).tolist()
        }).encode()
        t0 = time.perf_counter()
        req = urllib.request.Request(
            base + "/predict", data=payload,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as resp:
            out = json.loads(resp.read())
        dt = (time.perf_counter() - t0) * 1e3
        preds = np.asarray(out["predictions"])
        print(f"4 predictions in {dt:.1f} ms, shape {preds.shape}")
        assert preds.shape == (4, CLASSES)
        np.testing.assert_allclose(preds.sum(axis=1), 1.0, rtol=1e-4)

        with urllib.request.urlopen(base + "/metrics", timeout=10) as r:
            metrics = json.loads(r.read())
        print("serving metrics:", json.dumps(metrics)[:160], "...")
    finally:
        frontend.stop()
        serving.stop()
        redis.stop()
    print("OK")


if __name__ == "__main__":
    main()
