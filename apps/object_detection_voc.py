"""Object detection end-to-end on a VOC-format dataset (the reference's
`apps/object-detection` scenario extended with the full detection
vertical: reader → bbox-aware augmentation → SSD training → mAP →
visualization).

Flow: a Pascal-VOC-layout devkit on disk (synthetic "car" scenes) →
`PascalVoc` reader → the roi-consistent SSD augmentation chain (expand /
min-IoU crop / hflip with box remap) → SSD multibox training → VOC
mean-average-precision via `ObjectDetector.evaluate` → rendered
detections through the Visualizer.

    python apps/object_detection_voc.py
"""

import os
import tempfile

import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu import init_orca_context
from analytics_zoo_tpu.data import detection as dd
from analytics_zoo_tpu.models import objectdetection as od
from analytics_zoo_tpu.models.detection_zoo import Visualizer

SIZE = 64


def make_devkit(root, n_images=10, seed=4):
    import cv2
    rs = np.random.RandomState(seed)
    base = os.path.join(root, "VOC2007")
    for sub in ("ImageSets/Main", "Annotations", "JPEGImages"):
        os.makedirs(os.path.join(base, sub), exist_ok=True)
    ids = []
    for i in range(n_images):
        idx = f"{i:06d}"
        ids.append(idx)
        w, h = rs.randint(18, 32, 2)
        x1 = rs.randint(2, SIZE - w - 2)
        y1 = rs.randint(2, SIZE - h - 2)
        img = np.zeros((SIZE, SIZE, 3), np.uint8)
        img[y1:y1 + h, x1:x1 + w] = (255, 255, 255)
        cv2.imwrite(os.path.join(base, "JPEGImages", f"{idx}.jpg"),
                    cv2.cvtColor(img, cv2.COLOR_RGB2BGR))
        with open(os.path.join(base, "Annotations", f"{idx}.xml"),
                  "w") as fh:
            fh.write(
                f"<annotation><object><name>car</name>"
                f"<difficult>0</difficult><bndbox><xmin>{x1}</xmin>"
                f"<ymin>{y1}</ymin><xmax>{x1 + w}</xmax>"
                f"<ymax>{y1 + h}</ymax></bndbox></object></annotation>")
    with open(os.path.join(base, "ImageSets", "Main", "train.txt"),
              "w") as fh:
        fh.write("\n".join(ids) + "\n")
    return root


def main():
    init_orca_context(cluster_mode="local")
    devkit = make_devkit(tempfile.mkdtemp(prefix="voc_"))
    norm = lambda im: im.astype(np.float32) / 255.0     # noqa: E731

    x, gt = dd.load_ssd_train_set("voc_2007_train", devkit,
                                  resolution=SIZE, max_gt=4, seed=0,
                                  normalize=norm)
    xv, gv = dd.load_ssd_val_set("voc_2007_train", devkit,
                                 resolution=SIZE, max_gt=4,
                                 normalize=norm)
    print(f"{len(x)} augmented training images (roi chain: expand + "
          "min-IoU crop + hflip, boxes remapped)")

    n_classes = len(dd.VOC_CLASSES)
    model, anchors = od.build_ssd(n_classes=n_classes, image_size=SIZE)
    n_per_map = [8 * 8 * 3, 4 * 4 * 3]
    params = model.build(jax.random.PRNGKey(0))
    labels, loc_t, matched = jax.vmap(
        lambda b, l: od.match_anchors(b, l, jnp.asarray(anchors)))(
            jnp.asarray(gt["gt_boxes"]), jnp.asarray(gt["gt_labels"]))

    import optax
    opt = optax.adam(3e-3)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state):
        def loss_fn(p):
            flat = model.apply(p, jnp.asarray(x))
            loc, conf = od.split_ssd_output(flat, n_per_map, n_classes)
            return od.multibox_loss(conf, loc, labels, loc_t, matched)
        l, g = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = opt.update(g, opt_state)
        return optax.apply_updates(params, updates), opt_state, l

    for i in range(120):
        params, opt_state, l = step(params, opt_state)
    print(f"final multibox loss {float(l):.4f}")

    model.params = jax.device_get(params)
    det = od.ObjectDetector(model, anchors, n_per_map, n_classes,
                            label_map={i: c for i, c
                                       in enumerate(dd.VOC_CLASSES)})
    result = det.evaluate(xv, gv, classes=list(dd.VOC_CLASSES))
    ap_car = dict(result.ap_by_class())["car"]
    print(f"AP for car = {ap_car:.4f}")
    # headline mAP over classes PRESENT in the data (VOC convention:
    # absent classes don't dilute the mean)
    present = {dd.VOC_CLASSES[int(c)]
               for c in np.unique(gv["gt_labels"]) if c > 0}
    aps = [ap for name, ap in result.ap_by_class() if name in present]
    print(f"Mean AP over {len(aps)} present class(es) = "
          f"{float(np.mean(aps)):.4f}")
    assert ap_car > 0.5

    rows = det.predict(xv[:1], score_threshold=0.3)[0]
    canvas = Visualizer().draw((xv[0] * 255).astype(np.uint8), rows[:3])
    print(f"rendered {len(rows)} detections onto a "
          f"{canvas.shape} canvas; best: {rows[0][0]} "
          f"@ {rows[0][1]:.2f}")
    print("OK")


if __name__ == "__main__":
    main()
