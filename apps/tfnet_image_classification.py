"""TFNet image-classification inference — the reference's `apps/tfnet`
notebook (`image_classification_inference.ipynb`): a FROZEN TensorFlow
graph served for inference without retraining, preprocess → predict →
top-N labels. The notebook downloads a frozen ImageNet model; zero-egress
here, so the app trains a small TF model in-process, freezes it to a
GraphDef `.pb`, then runs the whole inference path through
`TFNet.from_frozen_graph` (`net.py` — the `TFNet.scala:56,657` role):
foreign-graph import, batched predict, top-N mapping, and the serving
wrapper (`to_inference_model`).

    python apps/tfnet_image_classification.py
"""

import os
import tempfile

import numpy as np

from analytics_zoo_tpu import init_orca_context
from analytics_zoo_tpu.net import TFNet

SIZE, CLASSES = 32, 4
LABELS = {0: "tabby", 1: "beagle", 2: "goldfish", 3: "airliner"}


def make_dataset(n=512, seed=0):
    """Class-separable thumbnails (mean color + stripe period)."""
    rs = np.random.RandomState(seed)
    y = rs.randint(CLASSES, size=n)
    x = np.zeros((n, SIZE, SIZE, 3), np.float32)
    for i, cls in enumerate(y):
        img = np.full((SIZE, SIZE, 3), 40.0 + 50.0 * cls, np.float32)
        img[:: 2 + cls] = 255.0 - img[:: 2 + cls]
        x[i] = img + rs.randn(SIZE, SIZE, 3) * 8.0
    return x / 255.0, y


def train_and_freeze(x, y, pb_path: str):
    """Train a small TF model (plain GradientTape loop — no Keras) and
    write a frozen GraphDef: the artifact the notebook downloads."""
    import tensorflow as tf
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2)

    rs = np.random.RandomState(1)
    k = tf.Variable(rs.randn(3, 3, 3, 8).astype(np.float32) * 0.1)
    w = tf.Variable(rs.randn(8, CLASSES).astype(np.float32) * 0.1)
    b = tf.Variable(np.zeros(CLASSES, np.float32))

    def forward(images):
        h = tf.nn.relu(tf.nn.conv2d(images, k, 1, "SAME"))
        h = tf.reduce_mean(h, axis=(1, 2))
        return tf.nn.softmax(h @ w + b)

    opt = tf.keras.optimizers.Adam(0.02)
    yt = tf.constant(y)
    xt = tf.constant(x)

    @tf.function
    def step():
        with tf.GradientTape() as tape:
            probs = forward(xt)
            loss = tf.reduce_mean(
                tf.keras.losses.sparse_categorical_crossentropy(yt, probs))
        grads = tape.gradient(loss, [k, w, b])
        opt.apply_gradients(zip(grads, [k, w, b]))
        return loss

    for _ in range(120):
        loss = step()
    print(f"TF train loss {float(loss):.4f}")

    fn = tf.function(forward).get_concrete_function(
        tf.TensorSpec([None, SIZE, SIZE, 3], tf.float32, name="images"))
    frozen = convert_variables_to_constants_v2(fn)
    tf.io.write_graph(frozen.graph.as_graph_def(),
                      os.path.dirname(pb_path),
                      os.path.basename(pb_path), as_text=False)
    return frozen


def main():
    init_orca_context(cluster_mode="local")
    x, y = make_dataset()
    pb = os.path.join(tempfile.mkdtemp(prefix="tfnet_"), "frozen.pb")
    frozen = train_and_freeze(x, y, pb)
    out_name = frozen.outputs[0].name          # e.g. 'Identity:0'
    print(f"frozen graph written: {pb} (output tensor {out_name!r})")

    net = TFNet.from_frozen_graph(pb, inputs=["images:0"],
                                  outputs=[out_name])
    probs = np.asarray(net.predict(x[:256], batch_per_thread=64))
    acc = float((np.argmax(probs, -1) == y[:256]).mean())
    print(f"TFNet accuracy on 256 images: {acc:.3f}")
    assert acc > 0.9, "frozen-graph inference should match training"

    # the notebook's top-N readout with a label map
    top = np.argsort(-probs[0])[:3]
    print("top-3 for image 0:",
          [(LABELS[int(i)], round(float(probs[0][i]), 3)) for i in top])

    # parity with direct TF execution of the same frozen graph
    direct = frozen(images=__import__("tensorflow").constant(
        x[:8]))[0].numpy()
    np.testing.assert_allclose(probs[:8], direct, rtol=1e-5, atol=1e-6)
    print("matches direct TF execution")

    # serving wrapper: the frozen graph behind the batching queue
    im = net.to_inference_model()
    out = np.asarray(im.predict(x[:4]))
    np.testing.assert_allclose(out, probs[:4], rtol=1e-5, atol=1e-6)
    print("serving InferenceModel parity OK")
    print("OK")


if __name__ == "__main__":
    main()
