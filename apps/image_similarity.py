"""Image similarity — embedding extraction + nearest-neighbor search
(the reference's `apps/image-similarity` notebook scenario).

Train a small CNN classifier on synthetic shape images, cut the graph at
the penultimate layer with `new_graph` (transfer surgery), use the
submodel as an embedding extractor, and retrieve nearest neighbors by
cosine similarity — same-class images should dominate the top hits.

    python apps/image_similarity.py
"""

import numpy as np

from analytics_zoo_tpu import init_orca_context
from analytics_zoo_tpu.keras import Input, Model
from analytics_zoo_tpu.keras import layers as L
from analytics_zoo_tpu.net import new_graph

SIZE = 24


def make_shapes(n=256, seed=0):
    """Three classes: filled square, hollow square, diagonal stripe."""
    rs = np.random.RandomState(seed)
    xs, ys = [], []
    for _ in range(n):
        c = rs.randint(3)
        img = 0.1 * rs.rand(SIZE, SIZE, 3).astype(np.float32)
        r0, c0 = rs.randint(2, 8, 2)
        s = rs.randint(10, 14)
        if c == 0:
            img[r0:r0 + s, c0:c0 + s] = 1.0
        elif c == 1:
            img[r0:r0 + s, c0:c0 + s] = 1.0
            img[r0 + 2:r0 + s - 2, c0 + 2:c0 + s - 2] = 0.1
        else:
            for i in range(s):
                img[r0 + i, c0 + i:min(c0 + i + 3, SIZE)] = 1.0
        xs.append(img)
        ys.append(c)
    return np.stack(xs), np.asarray(ys, np.int32)


def main():
    init_orca_context(cluster_mode="local")
    x, y = make_shapes()

    inp = Input(shape=(SIZE, SIZE, 3))
    h = L.Convolution2D(8, 3, 3, activation="relu",
                        border_mode="same")(inp)
    h = L.MaxPooling2D()(h)
    h = L.Flatten()(h)
    h = L.Dense(32, activation="relu", name="embedding")(h)
    out = L.Dense(3, activation="softmax")(h)
    model = Model(inp, out)
    model.compile("adam", "sparse_categorical_crossentropy", ["accuracy"])
    model.fit(x, y, batch_size=64, nb_epoch=6)

    # cut at the embedding layer (`NetUtils.newGraph` role)
    extractor = new_graph(model, output_layer_names=["embedding"])
    extractor.params = model.params
    emb = np.asarray(extractor.predict(x, batch_per_thread=64))
    emb = emb / np.linalg.norm(emb, axis=1, keepdims=True)

    # top-5 cosine neighbors for a few queries
    sims = emb @ emb.T
    np.fill_diagonal(sims, -1)
    hits = 0
    queries = range(10)
    for q in queries:
        top5 = np.argsort(-sims[q])[:5]
        hits += int((y[top5] == y[q]).sum())
        if q < 3:
            print(f"query class {y[q]}: neighbor classes {y[top5].tolist()}")
    precision_at_5 = hits / (len(list(queries)) * 5)
    print(f"precision@5 over 10 queries: {precision_at_5:.2f}")
    assert precision_at_5 > 0.6
    print("image similarity app OK")


if __name__ == "__main__":
    main()
