"""3D image augmentation (the reference's `apps/image-augmentation-3d`
notebook scenario).

Flow: synthetic volumetric "scans" (a bright ellipsoid lesion in a noisy
volume) → the 3D transform pipeline (random crop, rotation, affine
shear) → augmented volumes feed a small 3D conv classifier for a few
steps, showing the augmentation keeps labels learnable.

    python apps/image_augmentation_3d.py
"""

import numpy as np

from analytics_zoo_tpu import init_orca_context
from analytics_zoo_tpu.data.image3d import (AffineTransform3D,
                                            CenterCrop3D, RandomCrop3D,
                                            Rotate3D)

SIZE, CROP = 24, 16


def make_volume(has_lesion: bool, seed: int):
    """Noise volume; positives carry a bright ellipsoid off-center."""
    rs = np.random.RandomState(seed)
    vol = rs.rand(SIZE, SIZE, SIZE).astype(np.float32) * 0.2
    if has_lesion:
        c = rs.randint(8, 16, size=3)
        z, y, x = np.mgrid[0:SIZE, 0:SIZE, 0:SIZE]
        d = (((z - c[0]) / 3.0) ** 2 + ((y - c[1]) / 4.0) ** 2
             + ((x - c[2]) / 2.5) ** 2)
        vol += np.where(d < 1.0, 0.8, 0.0).astype(np.float32)
    return vol


def main():
    init_orca_context(cluster_mode="local")
    n_per_class = 12
    vols = [make_volume(lab == 1, seed=100 * lab + i)
            for lab in (0, 1) for i in range(n_per_class)]
    labels = np.array([0] * n_per_class + [1] * n_per_class, np.int32)

    rot = Rotate3D([0.0, 0.0, np.pi / 8])
    shear = AffineTransform3D(
        np.asarray([[1.0, 0.08, 0.0], [0.0, 1.0, 0.05],
                    [0.0, 0.0, 1.0]], np.float32))
    crop = RandomCrop3D(CROP, CROP, CROP, seed=3)

    augmented, kept_labels = [], []
    for vol, lab in zip(vols, labels):
        for k in range(3):                      # 3 augmented views each
            v = rot(vol) if k % 2 else vol
            v = shear(v) if k == 2 else v
            v = crop(v)
            augmented.append(v)
            kept_labels.append(lab)
    x = np.stack(augmented)[..., None]          # [N, D, H, W, 1]
    y = np.asarray(kept_labels, np.int32)
    print(f"{len(x)} augmented volumes of shape {x.shape[1:]}")
    assert x.shape[1:] == (CROP, CROP, CROP, 1)

    # eval-time path: deterministic center crop
    center = CenterCrop3D(CROP, CROP, CROP)
    xe = np.stack([center(v) for v in vols])[..., None]

    from analytics_zoo_tpu.keras import Sequential
    from analytics_zoo_tpu.keras import layers as L
    from analytics_zoo_tpu.learn.estimator import Estimator
    model = Sequential([
        L.Convolution3D(4, 3, 3, 3, input_shape=(CROP, CROP, CROP, 1),
                        border_mode="same", activation="relu"),
        L.MaxPooling3D(),
        L.Flatten(),
        L.Dense(2, activation="softmax"),
    ])
    model.compile(optimizer="adam",
                  loss="sparse_categorical_crossentropy")
    est = Estimator.from_keras(model)
    est.fit((x, y), epochs=12, batch_size=24)
    acc = float((np.argmax(model.predict(xe), -1) == labels).mean())
    print(f"accuracy on center-cropped volumes after augmented "
          f"training: {acc:.3f}")
    assert acc > 0.8, "augmentation must keep the lesion learnable"
    print("OK")


if __name__ == "__main__":
    main()
