"""Sentiment analysis — text pipeline to serving, end to end (the
reference's `apps/sentiment-analysis` notebook scenario).

Synthetic product reviews (templated positive/negative phrasing) flow
through the TextSet pipeline (tokenize → normalize → word2idx →
shape_sequence), train a TextClassifier, then serve it behind the
cluster-serving loop and classify a fresh review through the queue.

    python apps/sentiment_analysis.py
"""

import numpy as np

from analytics_zoo_tpu import init_orca_context
from analytics_zoo_tpu.data.text import TextSet
from analytics_zoo_tpu.models.textclassification import TextClassifier

SEQ_LEN = 12

POS = ["great", "excellent", "love", "wonderful", "perfect", "amazing",
       "fantastic", "best"]
NEG = ["terrible", "awful", "hate", "broken", "poor", "waste", "worst",
       "refund"]
FILLER = ["the", "this", "product", "really", "was", "is", "very",
          "quality", "shipping", "price", "it", "works"]


def make_reviews(n=512, seed=0):
    rs = np.random.RandomState(seed)
    texts, labels = [], []
    for _ in range(n):
        label = rs.randint(2)
        vocab = POS if label else NEG
        words = []
        for _ in range(rs.randint(6, SEQ_LEN)):
            pool = vocab if rs.rand() < 0.4 else FILLER
            words.append(pool[rs.randint(len(pool))])
        texts.append(" ".join(words))
        labels.append(label)
    return texts, labels


def main():
    init_orca_context(cluster_mode="local")
    texts, labels = make_reviews()
    tset = (TextSet.from_texts(texts, labels)
            .tokenize().normalize()
            .word2idx(min_freq=1)
            .shape_sequence(SEQ_LEN))
    x, y = tset.generate_sample()
    vocab = len(tset.get_word_index()) + 1
    print(f"{len(texts)} reviews, vocab {vocab}, x {x.shape}")

    tc = TextClassifier(class_num=2, embedding_dim=16, vocab_size=vocab,
                        sequence_length=SEQ_LEN, encoder="cnn",
                        encoder_output_dim=32)
    tc.model.compile("adam", "sparse_categorical_crossentropy",
                     ["accuracy"])
    hist = tc.model.fit(x, y, batch_size=64, nb_epoch=6)
    assert hist["loss"][-1] < hist["loss"][0]

    # serve it: queue in a review, read the sentiment back
    from analytics_zoo_tpu.serving import (ClusterServing, InferenceModel,
                                           InputQueue, MemoryBroker)
    im = InferenceModel().load_keras(tc)
    broker = MemoryBroker()
    serving = ClusterServing(im, broker).start()
    try:
        review = "this was excellent really love the quality"
        rx, _ = (TextSet.from_texts([review])
                 .tokenize().normalize()
                 .word2idx(existing_map=tset.get_word_index())
                 .shape_sequence(SEQ_LEN).generate_sample())
        probs = np.asarray(InputQueue(broker).predict(
            rx[0].astype(np.float32), timeout_s=30))
        sentiment = "positive" if probs.argmax() == 1 else "negative"
        print(f"review: {review!r} -> {sentiment} "
              f"(p={probs.max():.2f})")
        assert sentiment == "positive"
    finally:
        serving.stop()
    print("sentiment analysis app OK")


if __name__ == "__main__":
    main()
