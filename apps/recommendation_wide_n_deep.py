"""Wide & Deep recommendation (the reference's
`apps/recommendation-wide-n-deep` notebook scenario, BASELINE config 5).

Flow: a MovieLens-shaped ratings table → wide (one-hot base + crossed
gender×genre) and deep (embedding + indicator + continuous) feature
columns → `WideAndDeep` training through `Estimator.fit` → ranked-list
quality (NDCG@k / HitRate via the Ranker surface) → per-user top-N
recommendations.

    python apps/recommendation_wide_n_deep.py
"""

import numpy as np

from analytics_zoo_tpu import init_orca_context
from analytics_zoo_tpu.learn.estimator import Estimator
from analytics_zoo_tpu.models.recommendation import WideAndDeep

N_USERS, N_ITEMS = 60, 40
N_GENRES, N_AGE_BUCKETS = 5, 4


def make_ratings(n=3000, seed=0):
    """Synthetic taste structure: users in genre-affinity groups rate
    matching-genre items higher; age adds a mild effect."""
    rs = np.random.RandomState(seed)
    user = rs.randint(0, N_USERS, n)
    item = rs.randint(0, N_ITEMS, n)
    genre_of_item = item % N_GENRES
    taste_of_user = user % N_GENRES
    age_of_user = user % N_AGE_BUCKETS
    gender_of_user = user % 2
    affinity = (genre_of_item == taste_of_user).astype(np.float32)
    score = 0.25 + 0.55 * affinity + 0.1 * (age_of_user == 1) \
        + 0.05 * rs.rand(n)
    label = (score + 0.15 * rs.rand(n) > 0.6).astype(np.int32)
    return {"user": user, "item": item, "genre": genre_of_item,
            "age": age_of_user, "gender": gender_of_user, "label": label}


def to_features(t):
    """Assemble the four WideAndDeep input blocks from the table."""
    n = len(t["user"])
    # wide: one-hot genre + age (base) and gender x genre (cross)
    wide = np.zeros((n, N_GENRES + N_AGE_BUCKETS + 2 * N_GENRES),
                    np.float32)
    wide[np.arange(n), t["genre"]] = 1.0
    wide[np.arange(n), N_GENRES + t["age"]] = 1.0
    cross = t["gender"] * N_GENRES + t["genre"]
    wide[np.arange(n), N_GENRES + N_AGE_BUCKETS + cross] = 1.0
    # deep: indicator(age), embeddings(user, item), continuous(gender)
    ind = np.zeros((n, N_AGE_BUCKETS), np.float32)
    ind[np.arange(n), t["age"]] = 1.0
    emb = np.stack([t["user"], t["item"]], axis=1).astype(np.int32)
    cont = t["gender"].astype(np.float32)[:, None]
    return [wide, ind, emb, cont]


def main():
    init_orca_context(cluster_mode="local")
    table = make_ratings()
    x = to_features(table)
    y = table["label"]
    split = int(0.85 * len(y))
    xt = [a[:split] for a in x]
    xv = [a[split:] for a in x]
    yt, yv = y[:split], y[split:]

    wnd = WideAndDeep(
        class_num=2,
        wide_base_dims=(N_GENRES, N_AGE_BUCKETS),
        wide_cross_dims=(2 * N_GENRES,),
        indicator_dims=(N_AGE_BUCKETS,),
        embed_in_dims=(N_USERS, N_ITEMS),
        embed_out_dims=(8, 8),
        continuous_cols=("gender",),
        hidden_layers=(32, 16))
    wnd.model.compile(optimizer="adam",
                      loss="sparse_categorical_crossentropy",
                      metrics=["accuracy"])
    est = Estimator.from_keras(wnd.model)
    est.fit((xt, yt), epochs=40, batch_size=64)
    ev = est.evaluate((xv, yv), metrics=["accuracy"])
    print("held-out:", {k: round(v, 3) for k, v in ev.items()})
    assert ev["accuracy"] > 0.8

    # ranked-list quality on the held-out slice (Ranker mixin surface)
    probs = np.asarray(wnd.model.predict(xv))[:, 1]
    order = np.argsort(-probs)
    k = 20
    hit_at_k = float(yv[order[:k]].mean())
    print(f"precision of top-{k} ranked held-out pairs: {hit_at_k:.3f}")
    assert hit_at_k > yv.mean(), "ranking must beat the base rate"

    # per-user top-N from candidate pairs (Recommender surface shape)
    user0 = 7
    cand_items = np.arange(N_ITEMS)
    cand = {"user": np.full(N_ITEMS, user0), "item": cand_items,
            "genre": cand_items % N_GENRES,
            "age": np.full(N_ITEMS, user0 % N_AGE_BUCKETS),
            "gender": np.full(N_ITEMS, user0 % 2)}
    scores = np.asarray(wnd.model.predict(to_features(cand)))[:, 1]
    top = cand_items[np.argsort(-scores)][:5]
    print(f"top-5 items for user {user0} (taste genre "
          f"{user0 % N_GENRES}):", top.tolist())
    matches = sum(1 for i in top if i % N_GENRES == user0 % N_GENRES)
    assert matches >= 3, "recommendations should follow the user's taste"
    print("OK")


if __name__ == "__main__":
    main()
