"""High-dimensional anomaly detection with an autoencoder (the
reference's `apps/anomaly-detection-hd/autoencoder-zoo.ipynb` scenario).

Flow: multi-channel "sensor" telemetry → train a bottleneck autoencoder
on NORMAL traffic only → set the detection threshold from the training
reconstruction-error distribution → score a contaminated stream and
report precision/recall on the injected anomalies; the univariate
`zouwu.AEDetector` runs alongside on one channel for comparison.

    python apps/anomaly_detection_hd.py
"""

import numpy as np

from analytics_zoo_tpu import init_orca_context
from analytics_zoo_tpu.keras import Sequential
from analytics_zoo_tpu.keras import layers as L
from analytics_zoo_tpu.learn.estimator import Estimator
from analytics_zoo_tpu.zouwu import AEDetector

DIM = 32


def make_telemetry(n=2048, seed=0):
    """Correlated normal operation: a few latent drivers mixed into DIM
    channels + small noise. The mixing matrix is the PLANT's wiring —
    fixed across draws; only the latent activity varies."""
    mix = np.random.RandomState(99).randn(4, DIM)
    rs = np.random.RandomState(seed)
    latent = rs.randn(n, 4)
    return (latent @ mix + 0.1 * rs.randn(n, DIM)).astype(np.float32)


def inject_anomalies(x, rate=0.03, seed=1):
    rs = np.random.RandomState(seed)
    y = np.zeros(len(x), np.int32)
    idx = rs.choice(len(x), int(rate * len(x)), replace=False)
    x = x.copy()
    # anomalies break the cross-channel correlation structure
    x[idx] = rs.randn(len(idx), DIM).astype(np.float32) * 3.0
    y[idx] = 1
    return x, y


def main():
    init_orca_context(cluster_mode="local")
    normal = make_telemetry()
    mu, sd = normal.mean(0), normal.std(0) + 1e-6
    xn = (normal - mu) / sd

    ae = Sequential([
        L.Dense(16, input_shape=(DIM,), activation="relu"),
        L.Dense(4, activation="relu"),            # bottleneck
        L.Dense(16, activation="relu"),
        L.Dense(DIM),
    ])
    ae.compile(optimizer="adam", loss="mse")
    est = Estimator.from_keras(ae)
    est.fit((xn, xn), epochs=30, batch_size=128)

    def recon_error(batch):
        rec = np.asarray(ae.predict(batch))
        return np.mean((rec - batch) ** 2, axis=1)

    train_err = recon_error(xn)
    threshold = float(np.quantile(train_err, 0.995))
    print(f"threshold from normal traffic: {threshold:.4f}")

    stream, labels = inject_anomalies(make_telemetry(seed=7))
    err = recon_error((stream - mu) / sd)
    flagged = err > threshold
    tp = int(np.sum(flagged & (labels == 1)))
    fp = int(np.sum(flagged & (labels == 0)))
    fn = int(np.sum(~flagged & (labels == 1)))
    precision = tp / max(tp + fp, 1)
    recall = tp / max(tp + fn, 1)
    print(f"precision {precision:.3f}  recall {recall:.3f}  "
          f"({tp} tp / {fp} fp / {fn} fn)")
    assert recall > 0.9 and precision > 0.8

    # univariate comparison on channel 0 (zouwu surface)
    det = AEDetector(roll_len=16, epochs=10, ratio=0.05)
    det.fit(normal[:, 0])
    uni = det.score(stream[:, 0])
    print(f"AEDetector flagged {int(np.sum(uni))} windows on channel 0")
    print("OK")


if __name__ == "__main__":
    main()
