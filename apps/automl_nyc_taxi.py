"""AutoML time-series walkthrough — the reference's `apps/automl`
notebook (`nyc_taxi_dataset.ipynb`): NYC-taxi-style demand series →
`AutoTSTrainer` hyperparameter search → `TSPipeline` evaluate /
incremental fit / save / load / predict. Synthetic taxi demand stands in
for the download (daily + weekly seasonality, rush-hour bumps, noise).

    python apps/automl_nyc_taxi.py
"""

import os
import tempfile

import numpy as np
import pandas as pd

from analytics_zoo_tpu import init_orca_context
from analytics_zoo_tpu.automl.recipe import LSTMGridRandomRecipe
from analytics_zoo_tpu.zouwu.autots import AutoTSTrainer, TSPipeline


def taxi_demand_df(n=1200, seed=0) -> pd.DataFrame:
    """30-min interval series with daily (48) + weekly (336) rhythms —
    the shape of the notebook's `nyc_taxi.csv`."""
    rs = np.random.RandomState(seed)
    ts = np.arange(n)
    demand = (10.0
              + 4.0 * np.sin(2 * np.pi * ts / 48.0)        # daily
              + 2.0 * np.sin(2 * np.pi * ts / 336.0)       # weekly
              + 1.5 * ((ts % 48 == 17) | (ts % 48 == 36))  # rush hours
              + 0.4 * rs.randn(n))
    return pd.DataFrame({
        "datetime": pd.date_range("2015-01-01", periods=n, freq="30min"),
        "value": demand.astype(np.float32),
    })


def sparkline(vals, width=48) -> str:
    """The notebook's matplotlib plot, terminal edition."""
    blocks = "▁▂▃▄▅▆▇█"
    v = np.asarray(vals, np.float32)[:width]
    lo, hi = float(v.min()), float(v.max())
    span = (hi - lo) or 1.0
    return "".join(blocks[int((x - lo) / span * (len(blocks) - 1))]
                   for x in v)


def main():
    init_orca_context(cluster_mode="local")
    df = taxi_demand_df()
    split = int(len(df) * 0.8)
    train_df, test_df = df.iloc[:split], df.iloc[split:]
    print(f"{len(train_df)} train / {len(test_df)} test points")
    print("history:", sparkline(train_df["value"].to_numpy()[-96:]))

    trainer = AutoTSTrainer(dt_col="datetime", target_col="value",
                            horizon=1)
    pipeline = trainer.fit(train_df, validation_df=test_df,
                           recipe=LSTMGridRandomRecipe(
                               num_rand_samples=1, epochs=3, look_back=6),
                           metric="mse")
    print("best config:", {k: v for k, v in pipeline.config.items()
                           if k in ("lstm_1_units", "lstm_2_units", "lr",
                                    "past_seq_len")})

    metrics = pipeline.evaluate(test_df, metrics=("mse", "smape"))
    print(f"holdout: mse={metrics['mse']:.4f} smape={metrics['smape']:.2f}")

    preds = np.asarray(pipeline.predict(test_df)).ravel()
    actual = test_df["value"].to_numpy()[-len(preds):]
    print("actual:   ", sparkline(actual))
    print("predicted:", sparkline(preds))

    # incremental fit on the fresh window (notebook: fit on new data)
    pipeline.fit(test_df, epoch_num=2)
    metrics2 = pipeline.evaluate(test_df, metrics=("mse",))
    print(f"after incremental fit: mse={metrics2['mse']:.4f}")

    # save / load round trip, predictions must survive
    path = os.path.join(tempfile.mkdtemp(), "taxi_pipeline")
    pipeline.save(path)
    reloaded = TSPipeline.load(path)
    np.testing.assert_allclose(
        np.asarray(reloaded.predict(test_df)).ravel(),
        np.asarray(pipeline.predict(test_df)).ravel(), rtol=1e-5)
    print("save/load round trip OK")

    naive_mse = float(np.mean(np.diff(actual) ** 2))  # persistence model
    assert metrics2["mse"] < naive_mse * 1.5, (metrics2, naive_mse)
    print("OK")


if __name__ == "__main__":
    main()
