"""Headline benchmark: BERT-base classifier training MFU on one chip,
measured THROUGH the framework (`Estimator.from_keras(...).fit(...)`), not a
hand-rolled side loop — the engine's own hot path is what's timed, matching
the reference whose hot loop is its engine (`Topology.scala:1160-1337`).

Target from BASELINE.md: >=35% MFU. Prints ONE JSON line:
{"metric", "value", "unit", "vs_baseline"}.

Mixed precision: `fit(mixed_precision=True)` keeps f32 masters and runs
matmuls bf16 (MXU-native). `fit(steps_per_run=k)` fuses k steps into one
lax.scan program; the prefetch thread overlaps the next group's host→device
transfer with device compute. Set BENCH_TINY=1 for a seconds-scale smoke
run on CPU.
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax

# RngBitGenerator-backed keys: dropout bit generation under the default
# threefry costs ~25% of the BERT train step on v5e (34.7% -> 44.1% MFU).
# Matches the framework default (init_zoo_context flips to ZooConfig.prng_impl
# on TPU only; CPU smoke runs keep threefry like the framework does).
if ("JAX_DEFAULT_PRNG_IMPL" not in os.environ
        and jax.default_backend() == "tpu"):
    jax.config.update("jax_default_prng_impl", "rbg")

import numpy as np
import optax

_PEAK_BF16 = [  # device_kind substring -> peak bf16 FLOP/s per chip
    ("v6", 918e12),
    ("v5p", 459e12),
    ("v5e", 197e12),
    ("v5 lite", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 46e12),
]


def peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "cpu").lower()
    for sub, peak in _PEAK_BF16:
        if sub in kind:
            return peak
    return 197e12  # unknown TPU: assume v5e


def main():
    from analytics_zoo_tpu import init_orca_context
    from analytics_zoo_tpu.learn.estimator import Estimator
    from analytics_zoo_tpu.models.bert import BERTClassifier
    from analytics_zoo_tpu.ops import objectives

    tiny = os.environ.get("BENCH_TINY") == "1"
    if tiny:
        vocab, hidden, n_block, n_head, seq_len, inter = 512, 128, 2, 2, 64, 256
        batch, steps, steps_per_run = 8, 6, 3
    else:
        vocab, hidden, n_block, n_head, seq_len, inter = (
            30522, 768, 12, 12, 128, 3072)
        # batch 256 measures ~2-4 MFU points above 128 on v5e (more work
        # per dispatch amortizes the per-run host turnaround)
        batch = int(os.environ.get("BENCH_BATCH", 256))
        steps = int(os.environ.get("BENCH_STEPS", 48))
        steps_per_run = int(os.environ.get("BENCH_SPR", 24))

    init_orca_context(cluster_mode="local")
    dev = jax.devices()[0]

    use_flash = os.environ.get("BENCH_FLASH") == "1"
    model = BERTClassifier(
        num_classes=2, vocab=vocab, hidden_size=hidden, n_block=n_block,
        n_head=n_head, seq_len=seq_len, intermediate_size=inter,
        use_flash=use_flash)
    est = Estimator.from_keras(
        model, optimizer=optax.adamw(1e-4),
        loss=objectives.get("sparse_categorical_crossentropy",
                            from_logits=True))

    rs = np.random.RandomState(0)
    n = batch * steps
    data = {"x": [rs.randint(0, vocab, (n, seq_len)).astype(np.int32),
                  np.ones((n, seq_len), np.float32)],
            "y": rs.randint(0, 2, (n,)).astype(np.int32)}
    fit_kw = dict(epochs=1, batch_size=batch, steps_per_run=steps_per_run,
                  mixed_precision=True)

    est.fit(data, **fit_kw)                 # warmup: compile + first epoch
    t0 = time.perf_counter()
    hist = est.fit(data, **fit_kw)          # timed: cached program, real loop
    dt = time.perf_counter() - t0
    loss = hist["loss"][-1]

    # Matmul params only (embeddings are gathers, not FLOPs).
    n_params = sum(int(np.prod(np.shape(p))) for p in
                   jax.tree_util.tree_leaves(model.params))
    n_emb = (vocab + seq_len + 2) * hidden
    n_matmul = n_params - n_emb
    tokens = batch * seq_len
    # fwd+bwd = 6 FLOPs/param/token; attention scores+context add
    # 12 * L * T^2 * D per batch element (fwd 4*T^2*D, x3 with bwd).
    flops_step = 6 * n_matmul * tokens + 12 * n_block * seq_len**2 * hidden * batch
    flops_s = flops_step * steps / dt
    mfu = flops_s / peak_flops(dev)
    tokens_s = tokens * steps / dt

    print(json.dumps({
        "metric": "bert_base_train_mfu_via_estimator_fit",
        "value": round(mfu * 100, 2),
        "unit": "%",
        "vs_baseline": round(mfu / 0.35, 4),
        "tokens_per_sec": round(tokens_s, 1),
        "step_ms": round(dt / steps * 1e3, 2),
        "device": getattr(dev, "device_kind", str(dev)),
        "final_loss": float(loss),
    }))


if __name__ == "__main__":
    sys.exit(main())
