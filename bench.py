"""Headline benchmark: BERT-base classifier training MFU on one chip,
measured THROUGH the framework (`Estimator.from_keras(...).fit(...)`), not a
hand-rolled side loop — the engine's own hot path is what's timed, matching
the reference whose hot loop is its engine (`Topology.scala:1160-1337`).

Target from BASELINE.md: >=35% MFU. Prints ONE JSON line:
{"metric", "value", "unit", "vs_baseline"}.

Mixed precision: `fit(mixed_precision=True)` keeps f32 masters and runs
matmuls bf16 (MXU-native). `fit(steps_per_run=k)` fuses k steps into one
lax.scan program; the prefetch thread overlaps the next group's host→device
transfer with device compute. Set BENCH_TINY=1 for a seconds-scale smoke
run on CPU.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import jax

# honor JAX_PLATFORMS=cpu before anything initializes a backend (the
# machine's sitecustomize preimports jax with the TPU plugin pinned; a
# dead tunnel would otherwise hang even a CPU smoke run here)
if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
    jax.config.update("jax_platforms", "cpu")

# RngBitGenerator-backed keys: dropout bit generation under the default
# threefry costs ~25% of the BERT train step on v5e (34.7% -> 44.1% MFU).
# Matches the framework default (init_zoo_context flips to ZooConfig.prng_impl
# on TPU only; CPU smoke runs keep threefry like the framework does).
if ("JAX_DEFAULT_PRNG_IMPL" not in os.environ
        and jax.default_backend() == "tpu"):
    jax.config.update("jax_default_prng_impl", "rbg")

import numpy as np
import optax

from analytics_zoo_tpu.utils.roofline import peak_flops


def _measure_bert(dev, *, vocab, hidden, n_block, n_head, seq_len, inter,
                  batch, steps, steps_per_run, use_flash=False,
                  remat=False):
    """One BERT-classifier training measurement THROUGH Estimator.fit.
    Returns (mfu, tokens/s, step_ms, final_loss)."""
    from analytics_zoo_tpu.learn.estimator import Estimator
    from analytics_zoo_tpu.models.bert import BERTClassifier
    from analytics_zoo_tpu.ops import objectives

    drop_kw = {}
    if os.environ.get("BENCH_NODROP") == "1":   # roofline experiments
        drop_kw = dict(hidden_drop=0.0, attn_drop=0.0, dropout=0.0)
    model = BERTClassifier(
        num_classes=2, vocab=vocab, hidden_size=hidden, n_block=n_block,
        n_head=n_head, seq_len=seq_len, intermediate_size=inter,
        use_flash=use_flash, remat=remat,
        # scan-over-layers (stacked block params): collapses the Adam
        # phase but lax.scan's conservative residual saving OOMs the
        # batch-256/seq-2048 bench configs on a 16 GB chip and its
        # residual writes eat the win at batch 128 — measured wash;
        # docs/ROOFLINE.md round 5. Off by default.
        stacked=os.environ.get("BENCH_STACKED", "0") == "1", **drop_kw)
    est = Estimator.from_keras(
        model, optimizer=optax.adamw(1e-4),
        loss=objectives.get("sparse_categorical_crossentropy",
                            from_logits=True))

    rs = np.random.RandomState(0)
    n = batch * steps
    data = {"x": [rs.randint(0, vocab, (n, seq_len)).astype(np.int32),
                  np.ones((n, seq_len), np.float32)],
            "y": rs.randint(0, 2, (n,)).astype(np.int32)}
    fit_kw = dict(epochs=1, batch_size=batch, steps_per_run=steps_per_run,
                  mixed_precision=True,
                  # fused Pallas optimizer sweep (ISSUE 9): one HBM
                  # pass per leaf instead of optax's materialized-tree
                  # chain; BERT is compute-bound so the delta here is
                  # small — the A/B knob exists for the record
                  fused_optimizer=os.environ.get("BENCH_FUSED", "0") == "1")

    est.fit(data, **fit_kw)                 # warmup: compile + first epoch
    # Best of 3 timed epochs: the dev-tunnel chip's minute-to-minute
    # throughput swings +-15% (docs/ROOFLINE.md round-4 note); the
    # fastest full epoch is the sustained-throughput measurement, the
    # same program every time. The min-to-max spread of the timed epochs
    # is the session's observed noise — reported so round-over-round MFU
    # deltas inside it are read as noise, not progress (VERDICT r4 #8).
    times = []
    for _ in range(1 if os.environ.get("BENCH_TINY") == "1" else 3):
        t0 = time.perf_counter()
        hist = est.fit(data, **fit_kw)      # timed: cached program, real loop
        times.append(time.perf_counter() - t0)
    dt = min(times)
    noise_frac = (max(times) - dt) / dt if len(times) > 1 else 0.0

    # Matmul params only (embeddings are gathers, not FLOPs).
    n_params = sum(int(np.prod(np.shape(p))) for p in
                   jax.tree_util.tree_leaves(model.params))
    n_emb = (vocab + seq_len + 2) * hidden
    n_matmul = n_params - n_emb
    tokens = batch * seq_len
    # fwd+bwd = 6 FLOPs/param/token; attention scores+context add
    # 12 * L * T^2 * D per batch element (fwd 4*T^2*D, x3 with bwd).
    # Remat recomputation is NOT counted as useful work (honest MFU).
    flops_step = (6 * n_matmul * tokens
                  + 12 * n_block * seq_len**2 * hidden * batch)
    mfu = flops_step * steps / dt / peak_flops(dev)
    return (mfu, tokens * steps / dt, dt / steps * 1e3,
            float(hist["loss"][-1]), noise_frac, flops_step)


def _text(buf) -> str:
    """bytes/str/None → str (TimeoutExpired carries raw bytes even under
    text=True)."""
    if buf is None:
        return ""
    if isinstance(buf, bytes):
        return buf.decode(errors="replace")
    return buf


def _last_json(stdout):
    last = [ln for ln in _text(stdout).strip().splitlines()
            if ln.startswith("{")]
    return json.loads(last[-1]) if last else None


def _tail(stderr) -> str:
    return "\n".join(_text(stderr).strip().splitlines()[-8:])


def _run_sub(cmd, timeout, env=None):
    """Run a sibling benchmark; return `(json_or_None, timed_out)`. A
    failed child reports its stderr tail to OUR stderr — the driver's
    one shot at the round bench must not fail blind. The second element
    lets callers distinguish a fast crash (worth retrying) from a
    full-timeout hang (retrying doubles the cost)."""
    # unbuffered child stdout: a block-buffered JSON line would die with
    # the child's userspace buffer when a teardown hang forces a kill,
    # making the timeout-recovery path below a no-op
    env = dict(env if env is not None else os.environ, PYTHONUNBUFFERED="1")
    try:
        res = subprocess.run(cmd, capture_output=True, text=True,
                             timeout=timeout, env=env)
        r = _last_json(res.stdout)
        if r is not None:
            return r, False
        print(f"bench child {cmd[-1]} produced no JSON (rc={res.returncode})"
              f":\n{_tail(res.stderr)}", file=sys.stderr)
        return None, False
    except subprocess.TimeoutExpired as e:
        print(f"bench child {cmd[-1]} timed out after {timeout}s:"
              f"\n{_tail(e.stderr)}", file=sys.stderr)
        # a child can complete its measurement and then hang in runtime
        # teardown (known tunnel-rig mode): recover a JSON line it
        # already printed rather than nulling the field
        try:
            return _last_json(e.stdout), True
        except json.JSONDecodeError:
            return None, True
    except (OSError, json.JSONDecodeError) as e:
        print(f"bench child {cmd[-1]} failed: {e}", file=sys.stderr)
        return None, False


def _longseq_child():
    """Child-process mode: ONLY the seq-2048 flash measurement, printed
    as its own JSON line for the parent to merge.

    steps_per_run=24 fuses the whole epoch into one dispatch — measured
    -23 ms/step vs spr=6 (host turnaround through the tunnel is a real
    per-dispatch cost at batch 16)."""
    from analytics_zoo_tpu import init_orca_context
    init_orca_context(cluster_mode="local")
    dev = jax.devices()[0]
    m2k, t2k, ms2k, _, _, _ = _measure_bert(
        dev, vocab=30522, hidden=768, n_block=12, n_head=12,
        seq_len=2048, inter=3072,
        batch=int(os.environ.get("BENCH_LONGSEQ_BATCH", 16)),
        steps=24, steps_per_run=24, use_flash=True,
        remat=os.environ.get("BENCH_LONGSEQ_REMAT", "0") == "1")
    print(json.dumps({
        "bert_seq2048_flash_mfu_pct": round(m2k * 100, 2),
        "bert_seq2048_tokens_per_sec": round(t2k, 1),
        "bert_seq2048_step_ms": round(ms2k, 2),
    }))


def fit_scaling_summary(n_devices: int, counts=None, n_samples: int = 256,
                        batch_size: int = 64, hidden: int = 128,
                        seq_len: int = 32, n_block: int = 2) -> dict:
    """Training analogue of `bench_serving.multidevice_summary` (ISSUE 7):
    a data-parallel BERT fit scaling curve over 1→n devices — one GLOBAL
    batch split across the mesh's data axis, samples/sec per device
    count, per-device peak HBM from memwatch sampled during the timed
    fit — plus an fsdp-sharded fit of the same model recording the
    1/fsdp per-device params+opt_state footprint next to the replicated
    one. `host_cores`/`efficiency_vs_host_cores` report the forced-host
    ceiling exactly as the serving curve does: an M-core box caps
    scaling near M× regardless of virtual device count; on a real pod
    the ceiling is the chip count. Requires `len(jax.devices()) >=
    n_devices` (see `__graft_entry__.dryrun_multichip` for the re-exec
    wrapper)."""
    from analytics_zoo_tpu.common.config import MeshConfig
    from analytics_zoo_tpu.common.context import get_context
    from analytics_zoo_tpu.common.mesh import DeviceMesh
    from analytics_zoo_tpu.learn import trainer
    from analytics_zoo_tpu.observability.memwatch import DeviceMemoryWatcher
    from analytics_zoo_tpu.ops import objectives

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from __graft_entry__ import _build_bert_classifier

    devs = jax.devices()[:n_devices]
    assert len(devs) == n_devices, (
        f"need {n_devices} devices, have {len(devs)}")
    counts = sorted({c for c in (counts or [1, 2, n_devices])
                     if 1 <= c <= n_devices and batch_size % c == 0})

    rs = np.random.RandomState(0)
    x = {"ids": rs.randint(0, 128, (n_samples, seq_len)).astype(np.int32),
         "mask": np.ones((n_samples, seq_len), np.float32)}
    y = rs.randint(0, 2, (n_samples,)).astype(np.int32)
    loss_obj = objectives.get("sparse_categorical_crossentropy",
                              from_logits=True)

    def make_model():
        from analytics_zoo_tpu.learn.estimator import Estimator
        forward, params = _build_bert_classifier(
            vocab=128, hidden=hidden, n_block=n_block, n_head=4,
            seq_len=seq_len, intermediate=2 * hidden, n_classes=2,
            rng=jax.random.PRNGKey(0))

        def apply_fn(p, xb, training=False, rng=None):
            return forward(p, xb["ids"], xb["mask"], training=training,
                           rng=rng)

        est = Estimator.from_fn(apply_fn, lambda r, s: params, loss_obj,
                                optax.adam(1e-3))
        est.model.params = params
        return est.model

    def timed_fit(model, **kw):
        """One warm fit (compiles off the clock; the model's step memo
        carries to the next call), then the measured fit under a
        fast-sampling memory watcher."""
        trainer.fit_keras(model, x, y, batch_size=batch_size, epochs=1,
                          device_cache=False, seed=0, **kw)
        watcher = DeviceMemoryWatcher(interval_s=0.02,
                                      devices=devs).start()
        t0 = time.perf_counter()
        trainer.fit_keras(model, x, y, batch_size=batch_size, epochs=1,
                          device_cache=False, seed=1, **kw)
        dt = time.perf_counter() - t0
        snap = watcher.sample()
        watcher.stop()
        peaks = {label: e.get("peak_bytes", e["live_bytes"])
                 for label, e in snap.items()}
        steps = n_samples // batch_size
        return steps * batch_size / dt, peaks

    def state_footprint(mesh, rules):
        """Deterministic per-device params+opt_state bytes under a
        layout: place a fresh model's params (replicated or
        rule-sharded) plus an Adam state exactly as fit_keras would,
        and read the ACTUAL shard bytes (`memwatch.tree_device_bytes`)."""
        from analytics_zoo_tpu.learn.trainer import (_put_replicated,
                                                     _put_with_shardings)
        from analytics_zoo_tpu.observability.memwatch import \
            tree_device_bytes
        from analytics_zoo_tpu.parallel.sharding import tree_shardings
        model = make_model()
        opt = optax.adam(1e-3)
        if rules is not None:
            params = _put_with_shardings(
                model.params, tree_shardings(model.params, mesh, rules))
            opt_state = opt.init(params)
            opt_state = _put_with_shardings(
                opt_state, tree_shardings(opt_state, mesh, rules))
        else:
            params = _put_replicated(model.params, mesh)
            opt_state = _put_replicated(opt.init(params), mesh)
        per_dev = tree_device_bytes((params, opt_state))
        return round(max(per_dev.values()))

    ctx = get_context()
    prev_mesh = ctx.mesh
    sps, peak_by_count = {}, {}
    try:
        for c in counts:
            ctx.mesh = DeviceMesh(MeshConfig(data=c), devs[:c])
            rate, peaks = timed_fit(make_model())
            sps[str(c)] = round(rate, 1)
            peak_by_count[str(c)] = round(max(peaks.values()))
        # fsdp-sharded fit on the full mesh: same model, params +
        # opt_state at ~1/fsdp per device (the footprint the replicated
        # rows above pay in full)
        full_mesh = DeviceMesh(MeshConfig(data=1, fsdp=n_devices), devs)
        ctx.mesh = full_mesh
        srate, speaks = timed_fit(make_model(), sharding_rules=True)
        from analytics_zoo_tpu.parallel.sharding import TRANSFORMER_RULES
        state_replicated = state_footprint(full_mesh, None)
        state_sharded = state_footprint(full_mesh, TRANSFORMER_RULES)
        # tensor-parallel leg (ISSUE 12): same model on a
        # (data=1 × fsdp × tensor) factorization — the rule table's
        # column/row-parallel specs live, activations sharded over
        # tensor, state still ~1/(fsdp·tensor) per device
        tp_tensor = 2 if n_devices % 2 == 0 else 1
        tp_fsdp = n_devices // tp_tensor
        tp_mesh = DeviceMesh(MeshConfig(data=1, fsdp=tp_fsdp,
                                        tensor=tp_tensor), devs)
        ctx.mesh = tp_mesh
        tprate, tppeaks = timed_fit(make_model(), sharding_rules=True)
        tp_state = state_footprint(tp_mesh, TRANSFORMER_RULES)
    finally:
        ctx.mesh = prev_mesh

    base = sps[str(counts[0])]
    speedup = sps[str(counts[-1])] / max(base, 1e-9)
    cores = os.cpu_count() or 1
    return {
        "metric": "fit_scaling",
        "devices": n_devices,
        "host_cores": cores,
        "global_batch": batch_size,
        "samples_per_sec": sps,
        "scaling_speedup": round(speedup, 2),
        "scaling_efficiency": round(speedup / max(counts[-1], 1), 3),
        # forced-host devices burn real cores (see multidevice_summary):
        # the honest ceiling on an M-core box is min(devices, M)
        "efficiency_vs_host_cores": round(
            speedup / min(counts[-1], cores), 3),
        "per_device_peak_hbm_bytes": peak_by_count,
        "sharded_fsdp": {
            "fsdp": n_devices,
            "samples_per_sec": round(srate, 1),
            "per_device_peak_hbm_bytes": round(max(speaks.values())),
            # exact params+opt_state shard bytes per device, replicated
            # vs rule-sharded on the SAME mesh — the 1/fsdp memory claim
            # as a number (whole-process peaks above include batches,
            # prefetch copies and transients)
            "params_opt_bytes_per_device_replicated": state_replicated,
            "params_opt_bytes_per_device_sharded": state_sharded,
            "params_opt_shrink": round(
                state_replicated / max(state_sharded, 1), 2),
        },
        "sharded_tp": {
            "mesh": {"data": 1, "fsdp": tp_fsdp, "tensor": tp_tensor},
            "samples_per_sec": round(tprate, 1),
            "per_device_peak_hbm_bytes": round(max(tppeaks.values())),
            "params_opt_bytes_per_device": tp_state,
            "params_opt_shrink": round(
                state_replicated / max(tp_state, 1), 2),
        },
        "note": ("forced-host devices share the host's cores: fit "
                 f"scaling here caps near {min(n_devices, cores)}x; on "
                 "a real pod each chip computes off-host, so the "
                 "ceiling is the device count"),
    }


def input_pipeline_summary(tiny: bool = False, n_files: int = 8,
                           per_file: int = 512, dim: int = 64,
                           batch_size: int = 128, workers=(1, 4)) -> dict:
    """Input-pipeline A/B (ISSUE 15): the same small fit fed three ways
    — in-memory arrays (the ceiling: zero input work per step), and a
    TFRecord corpus streamed through the parallel shard pipeline at
    `pipeline_workers` 1 vs 4 — recording samples/sec, the per-leg
    `training_input_wait_ms` p50, and the `training_input_bound`
    verdict. The acceptance claim is pipeline-fed ≥ 0.9x in-memory at
    workers≥4; the single-worker leg is the baseline that shows what
    the worker pool buys. `host_effective_parallelism` (the PR 3/10
    spin-probe convention) records how many cores the host actually
    granted — on a starved box the 4-worker leg cannot beat that
    ceiling, and the JSON self-documents it."""
    import tempfile

    from analytics_zoo_tpu.data import tfrecord as tfr
    from analytics_zoo_tpu.data.dataset import TPUDataset
    from analytics_zoo_tpu.learn import trainer
    from analytics_zoo_tpu.observability import get_registry

    if tiny:
        n_files, per_file, batch_size = 4, 96, 32

    def make_model():
        from analytics_zoo_tpu.keras import Sequential
        from analytics_zoo_tpu.keras import layers as L
        model = Sequential([
            L.Dense(128, input_shape=(dim,), activation="relu"),
            L.Dense(64, activation="relu"),
            L.Dense(1, activation="sigmoid"),
        ])
        model.compile("adam", "binary_crossentropy")
        return model

    reg = get_registry()

    def leg(factory_ds, x=None, y=None):
        """Warm fit (compiles off the clock), cleared wait histogram,
        timed fit; returns (samples/sec, wait_p50_ms, input_bound)."""
        model = make_model()
        kw: dict = dict(batch_size=batch_size, epochs=1,
                        device_cache=False)
        if factory_ds is not None:
            n = factory_ds.n_samples()
            kw["x"], kw["y"] = None, None
            kw["batch_iter_factory"] = \
                lambda epoch: factory_ds.iter_train(1, seed=epoch)
        else:
            n = len(y)
            kw["x"], kw["y"] = x, y
        trainer.fit_keras(model, seed=0, **kw)
        wait_hist = reg.get("training_input_wait_ms")
        wait_hist.child().clear()
        t0 = time.perf_counter()
        trainer.fit_keras(model, seed=1, **kw)
        dt = time.perf_counter() - t0
        steps = n // batch_size
        p50 = wait_hist.percentile(0.5)
        bound = reg.get("training_input_bound").value()
        return (steps * batch_size / dt,
                round(0.0 if p50 != p50 else p50, 3), round(bound, 4))

    with tempfile.TemporaryDirectory() as d:
        rs = np.random.RandomState(0)
        for s in range(n_files):
            recs = []
            for _ in range(per_file):
                xv = rs.randn(dim).astype(np.float32)
                # ImageNet-style encoding: the feature rides as raw
                # bytes (one wire field — decodes at memory speed) and
                # parse_fn frombuffers it, like a real image corpus;
                # a float_list here would benchmark python varint
                # walking instead of the pipeline
                recs.append(tfr.encode_example({
                    "x": xv.tobytes(),
                    "y": np.asarray([float(xv.sum() > 0)], np.float32)}))
            tfr.write_tfrecord(os.path.join(d, f"part-{s:05d}.tfrecord"),
                               recs)

        def parse(ex):
            return (np.frombuffer(ex["x"][0], np.float32),
                    np.asarray(ex["y"], np.float32))

        def make_ds(w):
            return TPUDataset.from_tfrecord(
                os.path.join(d, "part-*.tfrecord"), parse,
                batch_size=batch_size, shuffle_buffer=1024,
                pipeline_workers=w)

        x_mem, y_mem = make_ds(1).materialize()
        mem_sps, _, _ = leg(None, x=np.asarray(x_mem), y=np.asarray(y_mem))

        sps, wait_p50, bound = {}, {}, {}
        for w in workers:
            sps[str(w)], wait_p50[str(w)], bound[str(w)] = leg(make_ds(w))

    try:
        from bench_serving import _measure_host_parallelism
        host_par = round(_measure_host_parallelism(1.0), 2)
    except Exception:  # noqa: BLE001 — the probe is advisory
        host_par = None

    w_hi = str(max(workers))
    w_lo = str(min(workers))
    return {
        "metric": "input_pipeline_ab",
        "corpus_records": n_files * per_file,
        "corpus_files": n_files,
        "batch_size": batch_size,
        "in_memory_samples_per_sec": round(mem_sps, 1),
        "pipeline_samples_per_sec": {k: round(v, 1)
                                     for k, v in sps.items()},
        "pipeline_vs_memory": round(sps[w_hi] / max(mem_sps, 1e-9), 3),
        "worker_speedup": round(sps[w_hi] / max(sps[w_lo], 1e-9), 2),
        "input_wait_p50_ms": wait_p50,
        "input_bound": bound,
        "host_cores": os.cpu_count() or 1,
        "host_effective_parallelism": host_par,
        "note": ("pipeline workers burn host cores: on a starved box "
                 "the multi-worker leg caps at the measured "
                 "host_effective_parallelism, not the worker count"),
    }


def main():
    from analytics_zoo_tpu import init_orca_context

    if os.environ.get("BENCH_LONGSEQ_CHILD") == "1":
        return _longseq_child()

    tiny = os.environ.get("BENCH_TINY") == "1"
    if tiny:
        cfg = dict(vocab=512, hidden=128, n_block=2, n_head=2, seq_len=64,
                   inter=256, batch=8, steps=6, steps_per_run=3)
    else:
        cfg = dict(
            vocab=30522, hidden=768, n_block=12, n_head=12, seq_len=128,
            inter=3072,
            # batch 256 measures ~2-4 MFU points above 128 on v5e (more
            # work per dispatch amortizes the per-run host turnaround)
            batch=int(os.environ.get("BENCH_BATCH", 256)),
            steps=int(os.environ.get("BENCH_STEPS", 48)),
            steps_per_run=int(os.environ.get("BENCH_SPR", 24)))

    init_orca_context(cluster_mode="local")
    dev = jax.devices()[0]

    mfu, tokens_s, step_ms, loss, noise, flops_step = _measure_bert(
        dev, use_flash=os.environ.get("BENCH_FLASH") == "1",
        remat=os.environ.get("BENCH_REMAT") == "1", **cfg)

    out = {
        "metric": "bert_base_train_mfu_via_estimator_fit",
        "value": round(mfu * 100, 2),
        "unit": "%",
        "vs_baseline": round(mfu / 0.35, 4),
        "tokens_per_sec": round(tokens_s, 1),
        "step_ms": round(step_ms, 2),
        # observed session noise as MFU points: round-over-round deltas
        # below this are tunnel-chip variance, not regressions/progress
        "mfu_noise_floor_pct": round(mfu * 100 * noise, 2),
        "device": getattr(dev, "device_kind", str(dev)),
        "final_loss": float(loss),
    }

    # cost-analysis roofline (ISSUE 6): the trainer's automatic
    # XLA-counted numbers for the SAME workload, no analytic flops
    # model. `mfu_agreement` is the acceptance check (within 10% of the
    # hand-counted headline) computed as a pure FLOP-count ratio —
    # cost flops/step over analytic flops/step — because MFU-over-MFU
    # would mix in the ±15% per-epoch timing swing (the accountant's
    # snapshot covers only the LAST timed fit, the headline the best
    # of 3; the timing basis cancels only in the FLOP ratio).
    try:
        from analytics_zoo_tpu.observability import get_accountant
        rl = get_accountant().snapshot("train")
        if rl.get("mfu") is not None:
            out["mfu_cost_analysis_pct"] = round(rl["mfu"] * 100, 2)
            cost_flops_step = rl["flops"] / max(cfg["steps"], 1)
            out["mfu_agreement"] = round(cost_flops_step / flops_step, 3) \
                if flops_step else None
            out["hbm_utilization_pct"] = round(
                rl["hbm_utilization"] * 100, 2) \
                if rl.get("hbm_utilization") is not None else None
    except Exception as e:  # noqa: BLE001 — the headline must survive
        print(f"roofline snapshot unavailable: {e}", file=sys.stderr)

    # Input-pipeline A/B (ISSUE 15): tfrecord-fed fit at workers 1 vs 4
    # against the in-memory ceiling, with the measured input-stall
    # gauges — the host-side leg of the roofline story. In-process (a
    # small CPU-side fit) and cheap enough to keep in every round.
    if os.environ.get("BENCH_INPUT", "1") == "1":
        try:
            ip = input_pipeline_summary(tiny=tiny)
            out["input_pipeline_sps_memory"] = \
                ip["in_memory_samples_per_sec"]
            out["input_pipeline_sps_workers"] = \
                ip["pipeline_samples_per_sec"]
            out["input_pipeline_vs_memory"] = ip["pipeline_vs_memory"]
            out["input_pipeline_worker_speedup"] = ip["worker_speedup"]
            out["input_pipeline_wait_p50_ms"] = ip["input_wait_p50_ms"]
            out["input_pipeline_input_bound"] = ip["input_bound"]
            out["input_pipeline_host_parallelism"] = \
                ip["host_effective_parallelism"]
        except Exception as e:  # noqa: BLE001 — headline must survive
            print(f"input-pipeline leg failed: {e}", file=sys.stderr)
            out["input_pipeline_vs_memory"] = None

    # Long-sequence headline: flash attention + per-block remat at seq
    # 2048 — the regime the Pallas kernels exist for (full-attention
    # activations would not fit; O(T) memory keeps the MXU busy).
    if not tiny and os.environ.get("BENCH_LONGSEQ", "1") == "1":
        # As a timeout-guarded subprocess (like NCF/serving below): a
        # hang or runtime-level abort on a smaller chip must never lose
        # the headline line.
        env = dict(os.environ, BENCH_LONGSEQ_CHILD="1")
        r, timed_out = _run_sub([sys.executable, os.path.abspath(__file__)],
                                timeout=1800, env=env)
        if r is None and not timed_out:
            # one retry on a FAST failure only: the dev-tunnel TPU worker
            # occasionally crashes under load and recovers within ~30 s —
            # a transient must not cost the round its long-sequence
            # headline. A timeout is a deterministic hang; retrying it
            # would double a ~30-minute wait for the same outcome.
            time.sleep(30)
            r, _ = _run_sub([sys.executable, os.path.abspath(__file__)],
                            timeout=1800, env=env)
        if r:
            out.update(r)
        else:
            out["bert_seq2048_flash_mfu_pct"] = None

    # The other two BASELINE targets, as guarded subprocesses so a hang or
    # crash in either can never lose the BERT headline (VERDICT r3 #3):
    # NCF throughput/HBM-utilization and serving p50/p99 over the RESP2
    # redis wire.
    here = os.path.dirname(os.path.abspath(__file__))
    if not tiny and os.environ.get("BENCH_NCF", "1") == "1":
        # BENCH_CALIBRATE=1 also runs the Adam-shaped streaming sweep in
        # this (timeout-guarded) child: the tunnel chip swings 10-20% day
        # to day on IDENTICAL programs, so the achieved-GB/s yardstick is
        # surfaced as session_hbm_gbps for reading cross-round MFU deltas
        # against the session, not just the noise floor.
        r, _ = _run_sub([sys.executable, os.path.join(here, "bench_ncf.py")],
                        timeout=900,
                        env=dict(os.environ, BENCH_CALIBRATE="1"))
        if r:
            out["ncf_samples_per_sec"] = r.get("value")
            out["ncf_hbm_utilization_pct"] = r.get("hbm_utilization_pct")
            out["ncf_step_ms"] = r.get("step_ms")
            out["ncf_bound"] = r.get("bound")
            out["session_hbm_gbps"] = r.get("achieved_hbm_gbps")
            out["session_mxu_tflops"] = r.get("achieved_mxu_tflops")
            if r.get("achieved_hbm_gbps") is not None:
                out["ncf_pct_of_achievable_bound"] = \
                    r.get("pct_of_achievable_bound")
            # the LIVE gauge version (ISSUE 6): XLA-counted bytes over
            # the calibrated session roofline, straight from
            # roofline_hbm_utilization{kind="train"} — BENCH r06+ tracks
            # the NCF roofline gap with no manual byte model
            for key in ("ncf_pct_of_achievable_bound_live",
                        "ncf_achieved_hbm_gbps_live"):
                if r.get(key) is not None:
                    out[key] = r.get(key)
        else:
            out["ncf_samples_per_sec"] = None
            out["session_hbm_gbps"] = None
            out["session_mxu_tflops"] = None
    if not tiny and os.environ.get("BENCH_SERVING", "1") == "1":
        # CPU backend for the serving stack: on dev rigs the TPU sits
        # behind an HTTP tunnel whose ~100 ms round trip per dispatch
        # would swamp the wire-path latency being measured (a production
        # v5e host runs the model in-process; bench_serving.py docstring)
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        # hermetic CPU child: keep the rig's TPU-plugin sitecustomize
        # (and its network relay) out of the wire-path measurement
        env.pop("PALLAS_AXON_POOL_IPS", None)
        r, _ = _run_sub([sys.executable,
                         os.path.join(here, "bench_serving.py")],
                        timeout=900, env=env)
        if r:
            out["serving_p50_ms"] = r.get("value")
            out["serving_p99_ms"] = r.get("p99_ms")
            out["serving_broker"] = r.get("broker")
            out["serving_wire_only_p50_ms"] = r.get("wire_only_p50_ms")
            # pipelined-engine sustained throughput (concurrent clients)
            for key in ("serving_concurrent_rps_pipelined",
                        "serving_concurrent_rps_sync",
                        "serving_pipeline_speedup",
                        "serving_concurrent_p50_ms",
                        "serving_concurrent_p99_ms",
                        "serving_drain_rps_pipelined",
                        "serving_drain_rps_sync",
                        "serving_drain_speedup",
                        "serving_warm_first_request_ms",
                        "serving_steady_p50_ms"):
                if r.get(key) is not None:
                    out[key] = r.get(key)
        else:
            out["serving_p50_ms"] = None
        # the model's forward ON the TPU (tunnel excluded), plus the int8
        # path; composed with the wire p50 above this is the full
        # production-host serving latency (VERDICT r4 #3)
        env = dict(os.environ, BENCH_DEVICE_FORWARD="1")
        r2, _ = _run_sub([sys.executable, os.path.join(here,
                                                       "bench_serving.py")],
                         timeout=900, env=env)
        if r2:
            for key in ("serving_device_forward_p50_ms",
                        "serving_device_forward_p99_ms",
                        "serving_device_forward_int8_p50_ms",
                        "serving_int8_speedup"):
                out[key] = r2.get(key)
            # compose PURE wire (identity model — no CPU forward counted)
            # with the on-chip forward; fall back to the full wire number
            # (slightly conservative) if the identity measure is absent
            wire = out.get("serving_wire_only_p50_ms") \
                or out.get("serving_p50_ms")
            if wire is not None \
                    and r2.get("serving_device_forward_p50_ms") is not None:
                out["serving_p50_ms_tpu"] = round(
                    wire + r2["serving_device_forward_p50_ms"], 2)
        else:
            out["serving_device_forward_p50_ms"] = None
        # chaos run (ISSUE 5): replica crash + slow replica + broker
        # outage against a live engine — quarantine detection time,
        # accepted-record loss (must be 0), post-recovery throughput
        if os.environ.get("BENCH_CHAOS", "1") == "1":
            env = dict(os.environ, JAX_PLATFORMS="cpu")
            env.pop("PALLAS_AXON_POOL_IPS", None)
            r3, _ = _run_sub([sys.executable,
                              os.path.join(here, "bench_serving.py"),
                              "--chaos"],
                             timeout=900, env=env)
            if r3:
                out["serving_chaos_record_loss"] = r3.get("value")
                for key in ("quarantine_detect_s", "quarantine_revive_s",
                            "slow_quarantine_detect_s",
                            "broker_outage_nans", "shed_records",
                            "post_recovery_ratio"):
                    if r3.get(key) is not None:
                        out["serving_chaos_" + key] = r3.get(key)
            else:
                out["serving_chaos_record_loss"] = None
        # fleet run (ISSUE 10): 2 engine PROCESSES co-consuming one
        # stream over the RESP2 wire — drain scaling vs single-engine
        # (host_cores caveat applies: engine processes burn real
        # cores), zero-loss through a mid-drain SIGKILL, shared-cache
        # cold-compile accounting
        if os.environ.get("BENCH_FLEET", "1") == "1":
            env = dict(os.environ, JAX_PLATFORMS="cpu")
            env.pop("PALLAS_AXON_POOL_IPS", None)
            r4, _ = _run_sub([sys.executable,
                              os.path.join(here, "bench_serving.py"),
                              "--engines", "2"],
                             timeout=900, env=env)
            if r4:
                for src, dst in (
                        ("fleet_drain_rps", "serving_fleet_drain_rps"),
                        ("fleet_speedup", "serving_fleet_speedup"),
                        ("fleet_efficiency", "serving_fleet_efficiency"),
                        ("efficiency_vs_host_cores",
                         "serving_fleet_efficiency_vs_host_cores"),
                        ("host_effective_parallelism",
                         "serving_fleet_host_effective_parallelism"),
                        ("fleet_zero_loss", "serving_fleet_zero_loss"),
                        ("engine_kill_redelivery_ms",
                         "serving_fleet_engine_kill_redelivery_ms"),
                        ("cold_compiles_per_bucket",
                         "serving_fleet_cold_compiles_per_bucket"),
                        ("survivor_claimed_records",
                         "serving_fleet_survivor_claimed_records")):
                    if r4.get(src) is not None:
                        out[dst] = r4.get(src)
            else:
                out["serving_fleet_drain_rps"] = None
        # chaos-rollout (ISSUE 14): publish a new checkpoint version to
        # a live 3-engine fleet, kill the gateway + one engine
        # mid-rollout, restart — convergence time to exactly one
        # version, zero accepted-record loss, and 0 XLA compiles from
        # the same-structure swaps
        if os.environ.get("BENCH_ROLLOUT", "1") == "1":
            env = dict(os.environ, JAX_PLATFORMS="cpu")
            env.pop("PALLAS_AXON_POOL_IPS", None)
            r7, _ = _run_sub([sys.executable,
                              os.path.join(here, "bench_serving.py"),
                              "--chaos-rollout"],
                             timeout=900, env=env)
            if r7:
                for src, dst in (
                        ("convergence_s", "serving_rollout_convergence_s"),
                        ("post_kill_convergence_s",
                         "serving_rollout_post_kill_convergence_s"),
                        ("records_lost", "serving_rollout_records_lost"),
                        ("zero_loss", "serving_rollout_zero_loss"),
                        ("final_versions",
                         "serving_rollout_final_versions"),
                        ("swap_compiles", "serving_rollout_swap_compiles"),
                        ("total_accepted",
                         "serving_rollout_total_accepted")):
                    if r7.get(src) is not None:
                        out[dst] = r7.get(src)
            else:
                out["serving_rollout_zero_loss"] = None
        # elastic replay (ISSUE 11): diurnal + spike trace against a
        # static fleet vs the autoscaled one — chip-seconds ratio,
        # per-phase p99 vs the declared SLO, light-load p50 A/B against
        # pad-to-largest dispatch, zero-loss + cold-compile accounting
        if os.environ.get("BENCH_ELASTIC", "1") == "1":
            env = dict(os.environ, JAX_PLATFORMS="cpu")
            env.pop("PALLAS_AXON_POOL_IPS", None)
            r5, _ = _run_sub([sys.executable,
                              os.path.join(here, "bench_serving.py"),
                              "--elastic"],
                             timeout=900, env=env)
            if r5:
                out["serving_elastic_chip_seconds_ratio"] = \
                    r5.get("chip_seconds_ratio")
                out["serving_elastic_slo_held"] = \
                    r5.get("elastic_slo_held")
                out["serving_elastic_zero_loss"] = r5.get("zero_loss")
                out["serving_elastic_scale_up_cold_compiles"] = \
                    r5.get("scale_up_cold_compiles")
                ab = r5.get("light_load_ab") or {}
                out["serving_elastic_light_p50_improvement_pct"] = \
                    ab.get("p50_improvement_pct")
            else:
                out["serving_elastic_chip_seconds_ratio"] = None
        # int8-vs-bf16 A/B through the full serving path (ISSUE 12):
        # pooled p50 per precision over one bucket set + parity; the
        # ≤0.6 acceptance ratio is an MXU property — on CPU rigs the
        # JSON's note self-documents the missing int8 kernel
        if os.environ.get("BENCH_INT8_AB", "1") == "1":
            env = dict(os.environ, JAX_PLATFORMS="cpu")
            env.pop("PALLAS_AXON_POOL_IPS", None)
            r6, _ = _run_sub([sys.executable,
                              os.path.join(here, "bench_serving.py"),
                              "--int8-ab"],
                             timeout=900, env=env)
            if r6:
                for src, dst in (
                        ("int8_p50_ms", "serving_int8_ab_p50_ms"),
                        ("bf16_p50_ms", "serving_bf16_ab_p50_ms"),
                        ("int8_vs_bf16_p50_ratio",
                         "serving_int8_vs_bf16_p50_ratio"),
                        ("int8_top1_agreement_vs_f32",
                         "serving_int8_top1_agreement"),
                        ("weight_shrink_vs_f32",
                         "serving_int8_weight_shrink")):
                    if r6.get(src) is not None:
                        out[dst] = r6.get(src)
            else:
                out["serving_int8_vs_bf16_p50_ratio"] = None
        # trace-overhead A/B (ISSUE 17): what fleet tracing costs the
        # drain — the acceptance bound is ≤2% at 1% head sampling; full
        # sampling and the /trace assembly latency ride along
        if os.environ.get("BENCH_TRACE", "1") == "1":
            env = dict(os.environ, JAX_PLATFORMS="cpu")
            env.pop("PALLAS_AXON_POOL_IPS", None)
            r7, _ = _run_sub([sys.executable,
                              os.path.join(here, "bench_serving.py"),
                              "--trace-overhead"],
                             timeout=900, env=env)
            if r7:
                out["serving_trace_off_rps"] = r7.get("trace_off_rps")
                out["serving_trace_1pct_rps"] = r7.get("trace_1pct_rps")
                out["serving_trace_full_rps"] = r7.get("trace_full_rps")
                out["serving_trace_overhead_1pct_pct"] = \
                    r7.get("trace_overhead_1pct_pct")
                out["serving_trace_assembly_ms"] = \
                    r7.get("trace_assembly_p50_ms")
            else:
                out["serving_trace_overhead_1pct_pct"] = None

    print(json.dumps(out))


if __name__ == "__main__":
    sys.exit(main())
