"""Headline benchmark: BERT-base classifier training MFU on one chip.

Target from BASELINE.md: >=35% MFU (the reference publishes no absolute
numbers, so the driver-set MFU target is the baseline). Prints ONE JSON line:
{"metric", "value", "unit", "vs_baseline"}.

Mixed precision: parameters live f32, matmuls run bf16 (MXU-native), softmax
statistics accumulate f32 (keras/transformer.py). Set BENCH_TINY=1 for a
seconds-scale smoke run on CPU.
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax

# RngBitGenerator-backed keys: dropout bit generation under the default
# threefry costs ~25% of the BERT train step on v5e (34.7% -> 44.1% MFU).
# Matches the framework default (init_zoo_context flips to ZooConfig.prng_impl
# on TPU only; CPU smoke runs keep threefry like the framework does).
if ("JAX_DEFAULT_PRNG_IMPL" not in os.environ
        and jax.default_backend() == "tpu"):
    jax.config.update("jax_default_prng_impl", "rbg")

import jax.numpy as jnp
import numpy as np
import optax

_PEAK_BF16 = [  # device_kind substring -> peak bf16 FLOP/s per chip
    ("v6", 918e12),
    ("v5p", 459e12),
    ("v5e", 197e12),
    ("v5 lite", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 46e12),
]


def peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "cpu").lower()
    for sub, peak in _PEAK_BF16:
        if sub in kind:
            return peak
    return 197e12  # unknown TPU: assume v5e


def main():
    from __graft_entry__ import _build_bert_classifier
    from analytics_zoo_tpu.ops import objectives

    tiny = os.environ.get("BENCH_TINY") == "1"
    if tiny:
        vocab, hidden, n_block, n_head, seq_len, inter = 512, 128, 2, 2, 64, 256
        batch, warmup, steps = 8, 1, 3
    else:
        vocab, hidden, n_block, n_head, seq_len, inter = (
            30522, 768, 12, 12, 128, 3072)
        batch, warmup, steps = int(os.environ.get("BENCH_BATCH", 128)), 2, 20

    dev = jax.devices()[0]
    forward, params = _build_bert_classifier(
        vocab=vocab, hidden=hidden, n_block=n_block, n_head=n_head,
        seq_len=seq_len, intermediate=inter, n_classes=2,
        rng=jax.random.PRNGKey(0))
    loss_obj = objectives.get("sparse_categorical_crossentropy",
                              from_logits=True)
    optimizer = optax.adamw(1e-4)
    opt_state = optimizer.init(params)

    def train_step(carry, _):
        params, opt_state, rng = carry
        rng, step_rng = jax.random.split(rng)

        def loss_fn(p):
            p_bf16 = jax.tree_util.tree_map(
                lambda a: a.astype(jnp.bfloat16)
                if a.dtype == jnp.float32 else a, p)
            # real training step: dropout active (BERT defaults 0.1)
            logits = forward(p_bf16, ids, mask, training=True, rng=step_rng)
            return loss_obj(labels, logits.astype(jnp.float32))
        loss, grads = jax.value_and_grad(loss_fn)(params)
        grads = jax.tree_util.tree_map(
            lambda g: g.astype(jnp.float32), grads)
        updates, opt_state2 = optimizer.update(grads, opt_state, params)
        return (optax.apply_updates(params, updates), opt_state2, rng), loss

    # All timed steps run inside ONE program (lax.scan) with a single host
    # readback at the end: remote-tunnel device APIs make per-step
    # block_until_ready unreliable, and this also removes host dispatch
    # overhead from the measurement.
    @jax.jit
    def run_steps(params, opt_state, rng):
        (params, opt_state, rng), losses = jax.lax.scan(
            train_step, (params, opt_state, rng), None, length=steps)
        return params, opt_state, rng, losses

    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, vocab, (batch, seq_len)), jnp.int32)
    mask = jnp.ones((batch, seq_len), jnp.float32)
    labels = jnp.asarray(rng.randint(0, 2, (batch,)), jnp.int32)

    key = jax.random.PRNGKey(0)
    for _ in range(warmup):
        params, opt_state, key, losses = run_steps(params, opt_state, key)
        np.asarray(losses[-1])  # force full execution (true device sync)
    t0 = time.perf_counter()
    params, opt_state, key, losses = run_steps(params, opt_state, key)
    loss = np.asarray(losses[-1])
    dt = time.perf_counter() - t0

    # Matmul params only (embeddings are gathers, not FLOPs).
    n_params = sum(int(np.prod(np.shape(p)))
                   for p in jax.tree_util.tree_leaves(params))
    n_emb = (vocab + seq_len + 2) * hidden
    n_matmul = n_params - n_emb
    tokens = batch * seq_len
    # fwd+bwd = 6 FLOPs/param/token; attention scores+context add
    # 12 * L * T^2 * D per batch element (fwd 4*T^2*D, x3 with bwd).
    flops_step = 6 * n_matmul * tokens + 12 * n_block * seq_len**2 * hidden * batch
    flops_s = flops_step * steps / dt
    mfu = flops_s / peak_flops(dev)
    tokens_s = tokens * steps / dt

    print(json.dumps({
        "metric": "bert_base_train_mfu",
        "value": round(mfu * 100, 2),
        "unit": "%",
        "vs_baseline": round(mfu / 0.35, 4),
        "tokens_per_sec": round(tokens_s, 1),
        "step_ms": round(dt / steps * 1e3, 2),
        "device": getattr(dev, "device_kind", str(dev)),
        "final_loss": float(loss),
    }))


if __name__ == "__main__":
    sys.exit(main())
