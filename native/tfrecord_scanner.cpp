// TFRecord frame scanner — the native fast path for the TFRecord data
// layer (analytics_zoo_tpu/data/tfrecord.py). The pure-Python CRC32C walk
// costs ~1 MB/s; this scans at memory bandwidth with a slice-by-8 CRC32C,
// verifying frame-header CRCs (and optionally payload CRCs) and returning
// record offsets/lengths for Python to mmap-slice.
//
// Exposed (C ABI, driven via ctypes from data/tfrecord.py):
//   tfr_scan(path, verify_payload, out_offsets, out_lengths, capacity)
//     -> record count (>=0), or -errno-style codes:
//        -1 open/read failure, -2 truncated, -3 corrupt length CRC,
//        -4 capacity too small, -5 corrupt payload CRC
//   tfr_count(path) -> record count with header verification (payloads
//     skipped), same error codes.
//   tfr_crc32c(buf, len) -> masked crc32c (for cross-checking with the
//     python implementation in tests)

#include <cstdint>
#include <cstdio>
#include <cstring>

namespace {

uint32_t table[8][256];

// ctypes releases the GIL, so scans can race from multiple threads:
// build the tables eagerly in a static initializer, not lazily behind a
// non-atomic flag.
struct TableInit {
  TableInit() {
    const uint32_t poly = 0x82F63B78u;
    for (uint32_t n = 0; n < 256; ++n) {
      uint32_t c = n;
      for (int k = 0; k < 8; ++k) c = (c & 1) ? (c >> 1) ^ poly : c >> 1;
      table[0][n] = c;
    }
    for (uint32_t n = 0; n < 256; ++n)
      for (int k = 1; k < 8; ++k)
        table[k][n] =
            table[k - 1][n] >> 8 ^ table[0][table[k - 1][n] & 0xFF];
  }
};
const TableInit table_init;

uint32_t crc32c(const uint8_t* p, size_t len, uint32_t crc = 0) {
  crc ^= 0xFFFFFFFFu;
  // slice-by-8
  while (len >= 8) {
    uint32_t lo;
    uint32_t hi;
    memcpy(&lo, p, 4);
    memcpy(&hi, p + 4, 4);
    lo ^= crc;
    crc = table[7][lo & 0xFF] ^ table[6][(lo >> 8) & 0xFF] ^
          table[5][(lo >> 16) & 0xFF] ^ table[4][lo >> 24] ^
          table[3][hi & 0xFF] ^ table[2][(hi >> 8) & 0xFF] ^
          table[1][(hi >> 16) & 0xFF] ^ table[0][hi >> 24];
    p += 8;
    len -= 8;
  }
  while (len--) crc = table[0][(crc ^ *p++) & 0xFF] ^ (crc >> 8);
  return crc ^ 0xFFFFFFFFu;
}

uint32_t masked(uint32_t crc) {
  return ((crc >> 15) | (crc << 17)) + 0xA282EAD8u;
}

uint32_t rd32(const uint8_t* p) {
  uint32_t v;
  memcpy(&v, p, 4);
  return v;
}

uint64_t rd64(const uint8_t* p) {
  uint64_t v;
  memcpy(&v, p, 8);
  return v;
}

// Shared frame walk. When offsets/lengths are null, only counts.
long scan_impl(const char* path, int verify_payload, int64_t* offsets,
               int64_t* lengths, long capacity) {
  FILE* fh = fopen(path, "rb");
  if (!fh) return -1;
  fseek(fh, 0, SEEK_END);
  long size = ftell(fh);
  fseek(fh, 0, SEEK_SET);

  long count = 0;
  long pos = 0;
  uint8_t header[12];
  // payload staging buffer (grown on demand) only when verifying payloads
  uint8_t* buf = nullptr;
  size_t buf_cap = 0;

  while (pos < size) {
    if (size - pos < 12 || fread(header, 1, 12, fh) != 12) {
      fclose(fh);
      delete[] buf;
      return -2;  // truncated header
    }
    uint64_t len = rd64(header);
    if (rd32(header + 8) != masked(crc32c(header, 8))) {
      fclose(fh);
      delete[] buf;
      return -3;  // corrupt length CRC
    }
    uint64_t remaining = (uint64_t)(size - pos) - 12;
    // overflow-safe: len + 4 would wrap for crafted lengths near 2^64
    if (remaining < 4 || len > remaining - 4) {
      fclose(fh);
      delete[] buf;
      return -2;  // truncated payload/CRC
    }
    if (offsets) {
      if (count >= capacity) {
        fclose(fh);
        delete[] buf;
        return -4;
      }
      offsets[count] = pos + 12;
      lengths[count] = (int64_t)len;
    }
    if (verify_payload) {
      if (len > buf_cap) {
        delete[] buf;
        buf_cap = (size_t)len;
        buf = new uint8_t[buf_cap];
      }
      uint8_t tail[4];
      if (fread(buf, 1, len, fh) != len || fread(tail, 1, 4, fh) != 4) {
        fclose(fh);
        delete[] buf;
        return -2;
      }
      if (rd32(tail) != masked(crc32c(buf, len))) {
        fclose(fh);
        delete[] buf;
        return -5;  // corrupt payload CRC
      }
    } else {
      fseek(fh, (long)len + 4, SEEK_CUR);
    }
    pos += 12 + (long)len + 4;
    ++count;
  }
  fclose(fh);
  delete[] buf;
  return count;
}

}  // namespace

extern "C" {

long tfr_scan(const char* path, int verify_payload, int64_t* offsets,
              int64_t* lengths, long capacity) {
  return scan_impl(path, verify_payload, offsets, lengths, capacity);
}

long tfr_count(const char* path) {
  return scan_impl(path, 0, nullptr, nullptr, 0);
}

uint32_t tfr_crc32c(const uint8_t* buf, long len) {
  return masked(crc32c(buf, (size_t)len));
}

}  // extern "C"
