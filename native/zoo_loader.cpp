// Native data loader: threaded shuffled batch assembly from an mmap'd
// record file.
//
// The TPU-native equivalent of the reference's native data-cache layer
// (PMEM NativeArray via JNI memkind, zoo/src/main/java/.../pmem/
// PersistentMemoryAllocator.java:37; pmem/FeatureSet.scala:151): samples
// live out-of-heap in a file-backed mapping (which IS how memkind fsdax PMEM
// works), and batch gather/shuffle runs on C++ worker threads off the Python
// GIL, overlapping host-side batch assembly with TPU step execution.
//
// Layout: one flat file of n_records fixed-size records (a record packs all
// pytree leaves' row bytes back to back; Python splits by offset).
//
// C ABI (ctypes):
//   void*   zoo_loader_create(path, n_records, record_bytes, batch_size,
//                             n_threads, queue_capacity, drop_remainder)
//   void    zoo_loader_start_epoch(l, seed, shuffle)  // also abandons any
//                                                     // half-read epoch
//   int64_t zoo_loader_next(l, out)   // rows copied; 0 = epoch end; -1 err
//   void    zoo_loader_destroy(l)

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <random>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct Batch {
    std::vector<uint8_t> data;
    int64_t rows = 0;
    uint64_t gen = 0;
};

struct Loader {
    // immutable config
    int fd = -1;
    const uint8_t* base = nullptr;
    size_t map_len = 0;
    int64_t n_records = 0;
    int64_t record_bytes = 0;
    int64_t batch_size = 0;
    int n_threads = 1;
    int queue_capacity = 4;
    bool drop_remainder = true;

    // epoch state (index values are always valid record ids, so a worker
    // racing an epoch restart reads a mix of old/new permutation — its
    // batch carries a stale gen and is discarded, never unsafe)
    std::vector<int64_t> index;
    std::atomic<int64_t> next_batch{0};
    int64_t n_batches = 0;
    uint64_t gen = 0;

    std::mutex mu;
    std::condition_variable cv_ready, cv_free;
    std::deque<Batch*> ready;
    std::deque<Batch*> free_bufs;
    std::vector<Batch*> all_bufs;
    int64_t delivered = 0;     // batches handed to the consumer this epoch
    bool shutting_down = false;

    std::vector<std::thread> workers;

    ~Loader() {
        {
            std::lock_guard<std::mutex> lk(mu);
            shutting_down = true;
        }
        cv_ready.notify_all();
        cv_free.notify_all();
        for (auto& t : workers) {
            if (t.joinable()) t.join();
        }
        for (auto* b : all_bufs) delete b;
        if (base) munmap(const_cast<uint8_t*>(base), map_len);
        if (fd >= 0) close(fd);
    }
};

void worker_loop(Loader* L) {
    for (;;) {
        Batch* buf = nullptr;
        uint64_t my_gen;
        int64_t b;
        {
            std::unique_lock<std::mutex> lk(L->mu);
            L->cv_free.wait(lk, [&] {
                return L->shutting_down ||
                       (L->next_batch.load(std::memory_order_relaxed) <
                            L->n_batches &&
                        !L->free_bufs.empty());
            });
            if (L->shutting_down) return;
            buf = L->free_bufs.front();
            L->free_bufs.pop_front();
            // claim the batch index under the SAME lock that start_epoch
            // holds while resetting (gen, next_batch) — so an index can
            // never be claimed for one epoch with another epoch's gen
            // (which would silently drop a batch and hang the consumer)
            my_gen = L->gen;
            b = L->next_batch.fetch_add(1, std::memory_order_relaxed);
        }
        if (b >= L->n_batches) {            // raced past the end: recycle
            std::lock_guard<std::mutex> lk(L->mu);
            L->free_bufs.push_back(buf);
            L->cv_free.notify_one();
            continue;
        }
        const int64_t start = b * L->batch_size;
        const int64_t rows =
            std::min(L->batch_size, L->n_records - start);
        for (int64_t r = 0; r < rows; ++r) {
            const int64_t rec = L->index[start + r];
            std::memcpy(buf->data.data() + r * L->record_bytes,
                        L->base + rec * L->record_bytes,
                        L->record_bytes);
        }
        buf->rows = rows;
        buf->gen = my_gen;
        {
            std::lock_guard<std::mutex> lk(L->mu);
            if (buf->gen != L->gen) {       // epoch restarted mid-copy
                L->free_bufs.push_back(buf);
                L->cv_free.notify_one();
                continue;
            }
            L->ready.push_back(buf);
        }
        L->cv_ready.notify_one();
    }
}

}  // namespace

extern "C" {

void* zoo_loader_create(const char* path, int64_t n_records,
                        int64_t record_bytes, int64_t batch_size,
                        int n_threads, int queue_capacity,
                        int drop_remainder) {
    if (n_records <= 0 || record_bytes <= 0 || batch_size <= 0) return nullptr;
    int fd = open(path, O_RDONLY);
    if (fd < 0) return nullptr;
    struct stat st;
    if (fstat(fd, &st) != 0 ||
        st.st_size < n_records * record_bytes) {
        close(fd);
        return nullptr;
    }
    size_t len = static_cast<size_t>(st.st_size);
    void* base = mmap(nullptr, len, PROT_READ, MAP_PRIVATE, fd, 0);
    if (base == MAP_FAILED) {
        close(fd);
        return nullptr;
    }
    auto* L = new Loader();
    L->fd = fd;
    L->base = static_cast<const uint8_t*>(base);
    L->map_len = len;
    L->n_records = n_records;
    L->record_bytes = record_bytes;
    L->batch_size = batch_size;
    L->n_threads = n_threads < 1 ? 1 : n_threads;
    L->queue_capacity = queue_capacity < 2 ? 2 : queue_capacity;
    L->drop_remainder = drop_remainder != 0;
    L->index.resize(n_records);
    for (int64_t i = 0; i < n_records; ++i) L->index[i] = i;
    for (int i = 0; i < L->queue_capacity; ++i) {
        auto* b = new Batch();
        b->data.resize(static_cast<size_t>(batch_size * record_bytes));
        L->all_bufs.push_back(b);
        L->free_bufs.push_back(b);
    }
    for (int i = 0; i < L->n_threads; ++i) {
        L->workers.emplace_back(worker_loop, L);
    }
    return L;
}

void zoo_loader_start_epoch(void* lp, uint64_t seed, int shuffle) {
    if (!lp) return;
    auto* L = static_cast<Loader*>(lp);
    std::lock_guard<std::mutex> lk(L->mu);
    L->gen++;
    // abandon any undelivered batches from a half-read epoch
    while (!L->ready.empty()) {
        L->free_bufs.push_back(L->ready.front());
        L->ready.pop_front();
    }
    for (int64_t i = 0; i < L->n_records; ++i) L->index[i] = i;
    if (shuffle) {
        std::mt19937_64 rng(seed);
        for (int64_t i = L->n_records - 1; i > 0; --i) {
            std::uniform_int_distribution<int64_t> d(0, i);
            std::swap(L->index[i], L->index[d(rng)]);
        }
    }
    L->n_batches = L->drop_remainder
        ? L->n_records / L->batch_size
        : (L->n_records + L->batch_size - 1) / L->batch_size;
    L->next_batch.store(0);
    L->delivered = 0;
    L->cv_free.notify_all();
}

int64_t zoo_loader_next(void* lp, uint8_t* out) {
    if (!lp || !out) return -1;
    auto* L = static_cast<Loader*>(lp);
    Batch* buf = nullptr;
    {
        std::unique_lock<std::mutex> lk(L->mu);
        if (L->delivered >= L->n_batches) return 0;   // epoch end
        L->cv_ready.wait(lk, [&] {
            return L->shutting_down || !L->ready.empty();
        });
        if (L->shutting_down) return -1;
        buf = L->ready.front();
        L->ready.pop_front();
        L->delivered++;
    }
    const int64_t rows = buf->rows;
    std::memcpy(out, buf->data.data(),
                static_cast<size_t>(rows * L->record_bytes));
    {
        std::lock_guard<std::mutex> lk(L->mu);
        L->free_bufs.push_back(buf);
    }
    L->cv_free.notify_one();
    return rows;
}

void zoo_loader_destroy(void* lp) {
    if (!lp) return;
    delete static_cast<Loader*>(lp);
}

}  // extern "C"
